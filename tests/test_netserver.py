"""Network front door (photon_ml_tpu/serving/netserver.py) and its
satellites: dual-framing decode into the shared admission path, binary
codec round-trips, typed wire errors that never poison window-mates,
per-connection backpressure edges (oversized, slowloris, mid-request
disconnect), drain-on-close, the SLO-adaptive admission controller
(serving/adaptive.py) and the replica fleet router (serving/router.py).
The FRONT-END semantics (coalescing, tenancy, hot swap) are covered by
test_serving_frontend.py; under test here is everything between a TCP
socket and ``ServingFrontend.score``."""

import asyncio
import json
import struct

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    LogisticRegressionModel,
    MatrixFactorizationModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    AdaptiveAdmission,
    AdaptiveAdmissionConfig,
    BucketLadder,
    FrontendConfig,
    NetClient,
    NetServer,
    NetServerConfig,
    ReplicaRouter,
    RouterConfig,
    ServerError,
    ServingFrontend,
    WindowedBurn,
)
from photon_ml_tpu.serving.netserver import (
    MalformedFrame,
    REQUEST_MAGIC,
    RESPONSE_MAGIC,
    dataset_from_json,
    decode_request,
    decode_response,
    encode_request,
    encode_response,
    json_payload,
    read_binary_response,
    read_http_response,
)
from photon_ml_tpu.types import TaskType

DT = jnp.float64

LADDER = dict(min_rows=8, max_rows=64)

_U4 = struct.Struct("<I")


def _dataset(rng, n=60, d=6, n_users=7, n_items=5):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    users = rng.integers(0, n_users, n).astype(str)
    items = rng.integers(0, n_items, n).astype(str)
    user_x = sp.csr_matrix(np.hstack(
        [rng.normal(0, 1, (n, 2)), np.ones((n, 1))]))
    return GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"global": sp.csr_matrix(x), "user": user_x},
        ids={"userId": users, "itemId": items})


def _game_model(rng, train):
    ds = build_random_effect_dataset(
        train, RandomEffectDataConfiguration("userId", "user"),
        intercept_col=2)
    re = RandomEffectModel.zeros_like_dataset(ds, dtype=DT)
    re = re.with_coefs([jnp.asarray(rng.normal(0, 1, np.asarray(c).shape))
                        for c in re.local_coefs])
    fe = FixedEffectModel(
        LogisticRegressionModel(Coefficients(
            jnp.asarray(rng.normal(0, 1, 6)))), "global")
    mf = MatrixFactorizationModel(
        "userId", "itemId",
        jnp.asarray(rng.normal(0, 1, (7, 3))),
        jnp.asarray(rng.normal(0, 1, (5, 3))),
        np.unique(train.id_columns["userId"].vocabulary),
        np.unique(train.id_columns["itemId"].vocabulary))
    return GameModel({"fixed": fe, "perUser": re, "mf": mf},
                     TaskType.LOGISTIC_REGRESSION)


def _frontend(rng, **cfg):
    train = _dataset(rng, n=60)
    gm = _game_model(rng, train)
    fe = ServingFrontend(
        {"default": gm}, dtype=DT, ladder=BucketLadder(**LADDER),
        config=FrontendConfig(**{"coalesce_window_s": 0.001,
                                 "max_pending": 256, **cfg}))
    return fe, gm


def _singles(seed0, k, n=1):
    return [_dataset(np.random.default_rng(seed0 + i), n=n)
            for i in range(k)]


# -- codecs ----------------------------------------------------------------


def test_binary_codec_roundtrip(rng):
    data = _dataset(rng, n=23)
    payload = encode_request(data, model="tenant-a")
    assert payload[:4] == REQUEST_MAGIC
    (n,) = _U4.unpack(payload[4:8])
    assert len(payload) == 8 + n
    out, model = decode_request(payload[8:])
    assert model == "tenant-a"
    assert out.num_rows == data.num_rows == 23
    assert sorted(out.feature_shards) == sorted(data.feature_shards)
    for name in data.feature_shards:
        a, b = data.feature_shards[name].tocsr(), out.feature_shards[name]
        np.testing.assert_array_equal(a.indptr, b.indptr)
        np.testing.assert_array_equal(a.indices, b.indices)
        assert a.data.tobytes() == np.asarray(b.data).tobytes()
    for name in data.id_columns:
        a, b = data.id_columns[name], out.id_columns[name]
        np.testing.assert_array_equal(a.codes, b.codes)
        assert list(a.vocabulary) == list(b.vocabulary)
    for field in ("responses", "offsets", "weights"):
        np.testing.assert_array_equal(getattr(data, field),
                                      getattr(out, field))


def test_binary_codec_rejects_malformed(rng):
    good = encode_request(_dataset(rng, n=9))[8:]
    # truncated payload: array reads run past the end
    with pytest.raises(MalformedFrame, match="truncated"):
        decode_request(good[:len(good) // 2])
    # trailing garbage after a complete decode
    with pytest.raises(MalformedFrame, match="trailing"):
        decode_request(good + b"\x00\x00")
    # meta is not JSON
    with pytest.raises(MalformedFrame, match="not valid JSON"):
        decode_request(_U4.pack(7) + b"notjson")
    # meta JSON but wrong schema
    meta = json.dumps({"model": "m"}).encode()
    with pytest.raises(MalformedFrame, match="meta schema"):
        decode_request(_U4.pack(len(meta)) + meta)
    # meta declares a shard whose arrays the payload doesn't carry
    bad_meta = json.dumps({"model": "m", "rows": 5,
                           "shards": [["global", 6, 10]],
                           "ids": [], "extras": []}).encode()
    with pytest.raises(MalformedFrame, match="truncated"):
        decode_request(_U4.pack(len(bad_meta)) + bad_meta)


def test_response_codec_ok_and_error():
    for dt in ("<f8", "<f4"):
        scores = np.arange(5, dtype=np.dtype(dt)) * 0.25
        frame = encode_response(scores)
        assert frame[:4] == RESPONSE_MAGIC
        out = decode_response(frame[8:])
        assert out.dtype == np.dtype(dt)
        assert out.tobytes() == scores.tobytes()
    frame = encode_response(None, ("shed", "queue full", "t-123"))
    with pytest.raises(ServerError) as ei:
        decode_response(frame[8:])
    assert ei.value.kind == "shed"
    assert ei.value.trace_id == "t-123"
    assert "queue full" in ei.value.message


def test_json_codec_roundtrip(rng):
    data = _dataset(rng, n=17)
    out, model = dataset_from_json(
        json.loads(json.dumps(json_payload(data, model="m"))))
    assert model == "m"
    assert out.num_rows == 17
    for name in data.feature_shards:
        a, b = data.feature_shards[name].tocsr(), \
            out.feature_shards[name].tocsr()
        # float repr round-trips doubles exactly
        assert a.data.tobytes() == np.asarray(b.data, np.float64).tobytes()
        np.testing.assert_array_equal(a.indices, b.indices)
        np.testing.assert_array_equal(a.indptr, b.indptr)
    for name in data.id_columns:
        a, b = data.id_columns[name], out.id_columns[name]
        np.testing.assert_array_equal(
            np.asarray(a.vocabulary)[a.codes],
            np.asarray(b.vocabulary)[b.codes])
    np.testing.assert_array_equal(data.responses, out.responses)


# -- end-to-end scoring over real sockets ----------------------------------


@pytest.mark.needs_f64
def test_wire_scores_byte_identical_both_framings(rng):
    """The acceptance contract: a framed request produces the SAME BYTES
    an in-process ``frontend.score()`` call returns — binary trivially
    (raw array bytes on the wire), HTTP because JSON float repr
    round-trips doubles exactly."""
    fe, _ = _frontend(rng)
    reqs = _singles(300, 5) + [_dataset(np.random.default_rng(399), n=20)]

    async def main():
        async with fe:
            want = [np.asarray(await fe.score(r)) for r in reqs]
            server = await NetServer(fe).start()
            try:
                async with NetClient("127.0.0.1", server.port) as c:
                    got_bin = [await c.score(r) for r in reqs]
                async with NetClient("127.0.0.1", server.port,
                                     framing="http") as c:
                    got_http = [await c.score(r) for r in reqs]
            finally:
                await server.close()
            st = server.stats()
            return want, got_bin, got_http, st

    want, got_bin, got_http, st = asyncio.run(main())
    for w, b, h in zip(want, got_bin, got_http):
        assert w.tobytes() == b.tobytes()
        assert w.tobytes() == h.tobytes()
    assert st["requests_binary"] == 6 and st["requests_http"] == 6
    assert st["responses"] == 12 and st["wire_errors"] == {}
    assert st["open_connections"] == 0


@pytest.mark.needs_f64
def test_malformed_frame_never_poisons_window_mates(rng):
    """One pipelined connection interleaves a malformed payload (honest
    frame length, garbage meta) between good frames while a SECOND
    connection scores concurrently: the bad frame gets a typed in-order
    error response, every good frame on both connections scores, and
    the per-kind error counter ticks exactly once."""
    fe, gm = _frontend(rng, coalesce_window_s=0.02)
    goods = _singles(500, 5)
    other = _dataset(np.random.default_rng(599), n=1)
    bad_payload = _U4.pack(7) + b"badmeta"
    bad_frame = REQUEST_MAGIC + _U4.pack(len(bad_payload)) + bad_payload

    telemetry.reset()
    telemetry.enable()
    try:

        async def main():
            async with fe:
                server = await NetServer(fe).start()
                try:
                    r, w = await asyncio.open_connection(
                        "127.0.0.1", server.port)
                    frames = [encode_request(d) for d in goods[:3]] \
                        + [bad_frame] \
                        + [encode_request(d) for d in goods[3:]]
                    w.write(b"".join(frames))
                    await w.drain()

                    async def mate():
                        async with NetClient("127.0.0.1",
                                             server.port) as c:
                            return await c.score(other)

                    mate_task = asyncio.ensure_future(mate())
                    got = []
                    for i in range(6):
                        if i == 3:
                            with pytest.raises(ServerError) as ei:
                                await read_binary_response(r)
                            assert ei.value.kind == "malformed"
                        else:
                            got.append(await read_binary_response(r))
                    w.close()
                    mate_scores = await mate_task
                    return got, mate_scores, server.stats()
                finally:
                    await server.close()

        got, mate_scores, st = asyncio.run(main())
        for d, s in zip(goods, got):
            np.testing.assert_allclose(s, gm.score(d),
                                       rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(mate_scores, gm.score(other),
                                   rtol=1e-10, atol=1e-10)
        assert st["wire_errors"] == {"malformed": 1}
        assert st["requests_binary"] == 7  # 5 good + 1 bad + window-mate
        assert st["responses"] == 6
        snap = telemetry.snapshot()
        assert snap["counters"]["serving.net.requests_binary"] == 7
        assert snap["counters"]["serving.net.wire_errors"] == 1
        assert snap["counters"]["serving.net.errors.malformed"] == 1
        assert snap["counters"]["serving.net.responses"] == 6
        assert snap["counters"]["serving.net.connections_opened"] == 2
    finally:
        telemetry.disable()
        telemetry.reset()


def test_binary_bad_magic_is_fatal(rng):
    """Mid-stream garbage where a frame magic should be: the stream
    position can't be trusted, so the server answers with a typed
    malformed frame and closes."""
    fe, _ = _frontend(rng)

    async def main():
        async with fe:
            server = await NetServer(fe).start()
            try:
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                # Valid magic opens the binary path; the NEXT frame's
                # magic is garbage (but not an HTTP head either).
                w.write(REQUEST_MAGIC + _U4.pack(4) + b"\x00\x00\x00\x00")
                await w.drain()
                with pytest.raises(ServerError) as ei:
                    await read_binary_response(r)  # the empty-ish frame
                assert ei.value.kind == "malformed"
                w.write(b"ZZZZ" + _U4.pack(0))
                await w.drain()
                with pytest.raises(ServerError) as ei:
                    await read_binary_response(r)
                assert ei.value.kind == "malformed"
                assert await r.read() == b""  # server closed
                return server.stats()
            finally:
                await server.close()

    st = asyncio.run(main())
    assert st["wire_errors"]["malformed"] == 2
    assert st["open_connections"] == 0


def test_oversized_frame_and_body_rejected(rng):
    fe, _ = _frontend(rng)
    cfg = NetServerConfig(max_body_bytes=4096)

    async def main():
        async with fe:
            server = await NetServer(fe, cfg).start()
            try:
                # binary: declared length over the bound -> typed
                # too_large, connection closed (payload never read)
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                w.write(REQUEST_MAGIC + _U4.pack(1 << 20))
                await w.drain()
                with pytest.raises(ServerError) as ei:
                    await read_binary_response(r)
                assert ei.value.kind == "too_large"
                assert await r.read() == b""
                w.close()
                # HTTP: Content-Length over the bound -> 413, closed
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                w.write(b"POST /score HTTP/1.1\r\n"
                        b"Content-Length: 1048576\r\n\r\n")
                await w.drain()
                status, body = await read_http_response(r)
                assert status == 413
                assert json.loads(body)["error"] == "too_large"
                assert await r.read() == b""
                w.close()
                return server.stats()
            finally:
                await server.close()

    st = asyncio.run(main())
    assert st["wire_errors"]["too_large"] == 2


def test_slowloris_header_timeout_both_framings(rng):
    fe, _ = _frontend(rng)
    cfg = NetServerConfig(header_timeout_s=0.15)

    async def main():
        async with fe:
            server = await NetServer(fe, cfg).start()
            try:
                # binary: magic arrives, the length head never does
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                w.write(REQUEST_MAGIC)
                await w.drain()
                with pytest.raises(ServerError) as ei:
                    await read_binary_response(r)
                assert ei.value.kind == "timeout"
                assert await r.read() == b""
                w.close()
                # HTTP: a first byte, then the header stalls
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                w.write(b"POST /sco")
                await w.drain()
                status, body = await read_http_response(r)
                assert status == 408
                assert json.loads(body)["error"] == "timeout"
                assert await r.read() == b""
                w.close()
                return server.stats()
            finally:
                await server.close()

    st = asyncio.run(main())
    assert st["wire_errors"]["timeout"] == 2


def test_mid_request_disconnect_counted_server_stays_up(rng):
    fe, _ = _frontend(rng)

    async def main():
        async with fe:
            server = await NetServer(fe).start()
            try:
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                # Frame head promises 100 bytes; 10 arrive, then hangup.
                w.write(REQUEST_MAGIC + _U4.pack(100) + b"x" * 10)
                await w.drain()
                w.close()
                # Wait for the handler to observe the disconnect.
                for _ in range(100):
                    if server.stats()["wire_errors"].get("disconnect"):
                        break
                    await asyncio.sleep(0.01)
                # The server is still healthy: a fresh connection gets
                # a clean /healthz.
                r2, w2 = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                w2.write(b"GET /healthz HTTP/1.1\r\n"
                         b"Connection: close\r\n\r\n")
                await w2.drain()
                status, body = await read_http_response(r2)
                w2.close()
                return status, json.loads(body), server.stats()
            finally:
                await server.close()

    status, body, st = asyncio.run(main())
    assert status == 200 and body["status"] == "ok"
    assert body["models"] == ["default"]
    assert st["wire_errors"] == {"disconnect": 1}
    assert st["open_connections"] == 0


def test_shed_and_unknown_model_typed_both_framings(rng):
    """Admission rejections and unknown tenants surface as TYPED wire
    errors (binary status byte / HTTP status), with the shed rejection
    carrying the front-end's trace id; neither closes the connection."""
    fe, _ = _frontend(rng)
    fe.max_pending = 0  # everything sheds at admission
    req = _dataset(np.random.default_rng(700), n=1)
    telemetry.reset()
    telemetry.enable(trace=True)  # tracing stamps the shed trace_id

    async def main():
        async with fe:
            server = await NetServer(fe).start()
            try:
                async with NetClient("127.0.0.1", server.port) as c:
                    with pytest.raises(ServerError) as shed_b:
                        await c.score(req)
                    with pytest.raises(ServerError) as unk_b:
                        await c.score(req, model="nope")
                async with NetClient("127.0.0.1", server.port,
                                     framing="http") as c:
                    with pytest.raises(ServerError) as shed_h:
                        await c.score(req)
                    with pytest.raises(ServerError) as unk_h:
                        await c.score(req, model="nope")
                return shed_b.value, unk_b.value, shed_h.value, \
                    unk_h.value, server.stats()
            finally:
                await server.close()

    try:
        shed_b, unk_b, shed_h, unk_h, st = asyncio.run(main())
    finally:
        telemetry.disable()
        telemetry.reset()
    assert shed_b.kind == shed_h.kind == "shed"
    assert shed_b.trace_id  # admission stamped a trace id
    assert unk_b.kind == unk_h.kind == "unknown_model"
    assert "nope" in unk_b.message
    assert st["wire_errors"] == {"shed": 2, "unknown_model": 2}
    # the connections survived their typed errors (2 requests each)
    assert st["requests_binary"] == 2 and st["requests_http"] == 2


@pytest.mark.needs_f64
def test_close_drains_inflight_request(rng):
    """The drain contract: a request already read off the socket when
    ``close()`` starts still settles through the front-end and its
    response reaches the client before the connection closes."""
    fe, gm = _frontend(rng, coalesce_window_s=0.25)
    req = _dataset(np.random.default_rng(800), n=1)

    async def main():
        async with fe:
            server = await NetServer(fe).start()
            r, w = await asyncio.open_connection("127.0.0.1", server.port)
            w.write(encode_request(req))
            await w.drain()
            await asyncio.sleep(0.05)  # frame read; window still open
            await server.close()  # must drain, not drop
            scores = await read_binary_response(r)
            assert await r.read() == b""  # then EOF
            w.close()
            return scores, server.stats()

    scores, st = asyncio.run(main())
    np.testing.assert_allclose(scores, gm.score(req),
                               rtol=1e-10, atol=1e-10)
    assert st["responses"] == 1 and st["wire_errors"] == {}


def test_http_keepalive_and_connection_close(rng):
    fe, _ = _frontend(rng)

    async def main():
        async with fe:
            server = await NetServer(fe).start()
            try:
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                for _ in range(2):  # keep-alive: two requests, one conn
                    w.write(b"GET /statz HTTP/1.1\r\n\r\n")
                    await w.drain()
                    status, body = await read_http_response(r)
                    assert status == 200
                assert json.loads(body)["net"]["requests_http"] == 2
                assert server.stats()["connections_opened"] == 1
                w.write(b"GET /healthz HTTP/1.1\r\n"
                        b"Connection: close\r\n\r\n")
                await w.drain()
                status, _ = await read_http_response(r)
                assert status == 200
                assert await r.read() == b""  # server honored close
                w.close()
                # unknown path -> 404, connection stays (keep-alive)
                r, w = await asyncio.open_connection(
                    "127.0.0.1", server.port)
                w.write(b"GET /nope HTTP/1.1\r\n\r\n")
                await w.drain()
                status, _ = await read_http_response(r)
                assert status == 404
                w.write(b"GET /healthz HTTP/1.1\r\n\r\n")
                await w.drain()
                status, _ = await read_http_response(r)
                assert status == 200
                w.close()
            finally:
                await server.close()

    asyncio.run(main())


# -- SLO-adaptive admission ------------------------------------------------


class _Knobs:
    """The two attributes the controller actuates — the rest of the
    front-end is irrelevant to the control law."""

    def __init__(self, max_pending=64, window=0.002):
        self.max_pending = max_pending
        self.coalesce_window_s = window


def test_adaptive_tighten_relax_hysteresis():
    burns = []
    fe = _Knobs()
    ctl = AdaptiveAdmission(fe, burn_fn=lambda: burns.pop(0))

    def run(*seq):
        burns.extend(seq)
        while burns:
            ctl.tick()

    # Over budget: tighten IMMEDIATELY, once per hot tick.
    run(2.0)
    assert fe.max_pending == 32
    assert fe.coalesce_window_s == pytest.approx(0.003)
    run(1.5)
    assert fe.max_pending == 16
    assert fe.coalesce_window_s == pytest.approx(0.0045)
    # Dead band: no actuation either way.
    run(0.7)
    assert fe.max_pending == 16
    # Quiet ticks accrue; relax only on the 4th CONSECUTIVE one.
    run(0.1, 0.1, 0.1)
    assert fe.max_pending == 16 and ctl.stats()["relaxes"] == 0
    run(0.1)
    assert fe.max_pending == 20  # 16 * 1.25
    assert fe.coalesce_window_s == pytest.approx(0.0045 * 0.75)
    # A dead-band tick RESETS the streak: 3 quiet + dead-band + 3 quiet
    # never relaxes; the 4th consecutive quiet tick does.
    run(0.1, 0.1, 0.1, 0.7, 0.1, 0.1, 0.1)
    assert ctl.stats()["relaxes"] == 1
    run(0.1)
    assert ctl.stats()["relaxes"] == 2
    assert fe.max_pending == 25
    # Sustained quiet converges EXACTLY to the configured baseline and
    # never overshoots it.
    run(*([None] * 40))
    assert fe.max_pending == 64
    assert fe.coalesce_window_s == pytest.approx(0.002)
    relaxes = ctl.stats()["relaxes"]
    run(*([0.0] * 8))  # at base: quiet ticks are no-ops
    assert ctl.stats()["relaxes"] == relaxes
    assert fe.max_pending == 64
    # Pending floor under sustained overload.
    run(*([5.0] * 12))
    assert fe.max_pending == 1
    assert fe.coalesce_window_s == pytest.approx(0.05)  # window cap


def test_adaptive_dry_run_and_validation():
    fe = _Knobs()
    ctl = AdaptiveAdmission(
        fe, burn_fn=lambda: 9.9,
        config=AdaptiveAdmissionConfig(apply=False))
    for _ in range(5):
        ctl.tick()
    st = ctl.stats()
    assert st["ticks"] == 5 and st["tightens"] == 5
    assert st["apply"] is False
    assert fe.max_pending == 64  # measured, never actuated
    assert fe.coalesce_window_s == 0.002
    assert st["last_burn"] == 9.9
    with pytest.raises(ValueError, match="slo_specs"):
        AdaptiveAdmission(_Knobs())  # no steering source


def test_windowed_burn_measures_per_tick():
    """Burn reflects ONLY traffic since the previous measure() — the
    controller must not steer on process-lifetime averages — and the
    worst objective wins."""
    telemetry.reset()
    telemetry.enable()
    try:
        h = telemetry.histogram("t.lat_seconds")
        wb = WindowedBurn(["p99:t.lat_seconds<=10ms",
                           "ratio:t.rej/t.adm<=0.1"])
        h.observe(0.001, n=100)  # all fast
        b = wb.measure()
        assert b is not None and b < 0.5
        assert wb.measure() is None  # no new traffic this tick
        h.observe(1.0, n=50)  # every sample blows the threshold
        assert wb.measure() > 1.0
        # Counter objectives diff the same way; the max across
        # objectives steers (latency saw nothing this tick).
        telemetry.counter("t.adm").inc(100)
        telemetry.counter("t.rej").inc(50)
        assert wb.measure() == pytest.approx(5.0)  # (50/100) / 0.1
        # Old counts never leak into the next tick's ratio.
        telemetry.counter("t.adm").inc(100)
        assert wb.measure() == pytest.approx(0.0)
    finally:
        telemetry.disable()
        telemetry.reset()


# -- replica router --------------------------------------------------------


@pytest.mark.needs_f64
def test_router_spreads_and_is_byte_transparent(rng):
    """Pipelined frames through the router fan out across replicas
    (least-pending, per-REQUEST routing) and come back in request
    order, byte-identical to a direct in-process score."""
    fe_a, gm = _frontend(rng)
    fe_b = ServingFrontend(
        {"default": gm}, dtype=DT, ladder=BucketLadder(**LADDER),
        config=FrontendConfig(coalesce_window_s=0.001, max_pending=256))
    reqs = _singles(900, 10)

    async def main():
        async with fe_a:
            async with fe_b:
                servers = [await NetServer(f).start()
                           for f in (fe_a, fe_b)]
                router = await ReplicaRouter(
                    [("127.0.0.1", s.port) for s in servers]).start()
                try:
                    r, w = await asyncio.open_connection(
                        "127.0.0.1", router.port)
                    w.write(b"".join(encode_request(d) for d in reqs))
                    await w.drain()
                    got = [await read_binary_response(r)
                           for _ in range(len(reqs))]
                    w.close()
                    return got, router.stats()
                finally:
                    await router.close()
                    for s in servers:
                        await s.close()

    got, st = asyncio.run(main())
    for d, s in zip(reqs, got):
        np.testing.assert_allclose(s, gm.score(d),
                                   rtol=1e-10, atol=1e-10)
    assert st["forwarded"] == st["returned"] == 10
    assert st["backend_errors"] == 0
    spread = [b["forwarded"] for b in st["backends"]]
    assert all(n > 0 for n in spread) and sum(spread) == 10


def test_router_cold_start_concurrent_clients_one_conn_per_backend():
    """Regression: clients racing through a cold router must not open
    duplicate connections to one backend. The connect race used to
    spawn duplicate pumps that fought over the shared reader, tore the
    response framing, and closed the live connection out from under
    every in-flight request."""

    async def main():
        conn_counts = [0, 0]
        ok = encode_response(np.ones(1, dtype=np.float64))

        def handler_for(idx):
            async def handle(reader, writer):
                conn_counts[idx] += 1
                try:
                    while True:
                        head = await reader.readexactly(8)
                        (n,) = _U4.unpack(head[4:])
                        await reader.readexactly(n)
                        writer.write(ok)
                        await writer.drain()
                except (asyncio.IncompleteReadError, ConnectionError):
                    pass
            return handle

        backends = [await asyncio.start_server(
            handler_for(i), host="127.0.0.1", port=0) for i in range(2)]
        ports = [s.sockets[0].getsockname()[1] for s in backends]
        router = await ReplicaRouter(
            [("127.0.0.1", p) for p in ports]).start()
        frame = REQUEST_MAGIC + _U4.pack(4) + b"xxxx"
        per = 25

        async def client():
            r, w = await asyncio.open_connection(
                "127.0.0.1", router.port)
            w.write(frame * per)
            await w.drain()
            got = [await read_binary_response(r) for _ in range(per)]
            w.close()
            return got

        try:
            results = await asyncio.gather(*[client() for _ in range(8)])
            st = router.stats()
        finally:
            await router.close()
            for s in backends:
                s.close()
                await s.wait_closed()
        return results, st, conn_counts

    results, st, conn_counts = asyncio.run(main())
    assert [len(g) for g in results] == [25] * 8
    assert st["backend_errors"] == 0
    assert st["forwarded"] == st["returned"] == 200
    # The sharp assertion: one persistent connection per backend, no
    # matter how many clients raced the first pick.
    assert conn_counts == [1, 1]


def test_router_backend_death_is_typed_internal_error():
    """A backend connection that dies mid-request fails its in-flight
    requests with a typed ``internal`` frame — clients never hang —
    and the backend is retried via reconnect on the next pick."""

    async def main():
        async def eat_and_close(reader, writer):
            head = await reader.readexactly(8)
            (n,) = _U4.unpack(head[4:])
            await reader.readexactly(n)
            writer.close()  # dies without answering

        backend = await asyncio.start_server(
            eat_and_close, host="127.0.0.1", port=0)
        port = backend.sockets[0].getsockname()[1]
        router = await ReplicaRouter([("127.0.0.1", port)]).start()
        try:
            frame = REQUEST_MAGIC + _U4.pack(4) + b"xxxx"
            errs = []
            r, w = await asyncio.open_connection("127.0.0.1", router.port)
            for _ in range(2):  # second request exercises reconnect
                w.write(frame)
                await w.drain()
                try:
                    await read_binary_response(r)
                except ServerError as e:
                    errs.append(e)
            w.close()
            return errs, router.stats()
        finally:
            await router.close()
            backend.close()
            await backend.wait_closed()

    errs, st = asyncio.run(main())
    assert [e.kind for e in errs] == ["internal", "internal"]
    assert "backend connection lost" in errs[0].message
    assert st["backend_errors"] == 2 and st["forwarded"] == 2


def test_router_rejects_malformed_magic():
    async def main():
        async def never_called(reader, writer):
            writer.close()

        backend = await asyncio.start_server(
            never_called, host="127.0.0.1", port=0)
        port = backend.sockets[0].getsockname()[1]
        router = await ReplicaRouter(
            [("127.0.0.1", port)],
            RouterConfig(max_body_bytes=1024)).start()
        try:
            r, w = await asyncio.open_connection("127.0.0.1", router.port)
            w.write(b"GET /score HTTP/1.1\r\n\r\n")  # HTTP at the router
            await w.drain()
            with pytest.raises(ServerError) as ei:
                await read_binary_response(r)
            assert ei.value.kind == "malformed"
            assert await r.read() == b""
            w.close()
            # oversized declared frame: typed too_large, closed
            r, w = await asyncio.open_connection("127.0.0.1", router.port)
            w.write(REQUEST_MAGIC + _U4.pack(1 << 20))
            await w.drain()
            with pytest.raises(ServerError) as ei:
                await read_binary_response(r)
            assert ei.value.kind == "too_large"
            w.close()
            return router.stats()
        finally:
            await router.close()
            backend.close()
            await backend.wait_closed()

    st = asyncio.run(main())
    assert st["malformed"] == 2 and st["forwarded"] == 0
