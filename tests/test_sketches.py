"""telemetry/sketches.py: merge associativity and order-determinism
(bitwise-equal serialized state across merge trees), quantile error
bounds vs exact order statistics on adversarial streams, heavy-hitter
guarantees, empty/single-element sketches, drift-score math, and the
ConvergenceRing bound."""

import numpy as np
import pytest

from photon_ml_tpu.optimization.convergence import ConvergenceRing
from photon_ml_tpu.telemetry.sketches import (
    MomentsSketch,
    QuantileSketch,
    TopKSketch,
    ks,
    psi,
    sketch_from_state,
)


def _adversarial_streams(rng):
    """Streams picked to stress the bucket grid: heavy ties, 40 orders
    of magnitude of dynamic range, signed mixtures, sorted/reversed
    order, near-zero clusters."""
    base = np.concatenate([
        rng.lognormal(0, 3, 4000),            # heavy right tail
        -rng.lognormal(1, 2, 3000),           # signed
        np.full(1500, 2.5),                   # massive tie block
        np.zeros(800),                        # zeros
        rng.normal(0, 1e-12, 400),            # near-zero cluster
        10.0 ** rng.uniform(-20, 20, 300),    # 40 decades
    ])
    shuffled = base.copy()
    rng.shuffle(shuffled)
    return {
        "shuffled": shuffled,
        "sorted": np.sort(base),
        "reversed": np.sort(base)[::-1],
        "ties_only": np.full(997, -7.25),
    }


def _exact_quantile(sorted_vals, q):
    return sorted_vals[int(np.floor(q * (len(sorted_vals) - 1)))]


def test_quantile_relative_error_bound_adversarial():
    rng = np.random.default_rng(7)
    alpha = 0.01
    for name, data in _adversarial_streams(rng).items():
        sk = QuantileSketch(alpha)
        sk.update(data)
        exact = np.sort(data)
        for q in (0.0, 0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0):
            e = _exact_quantile(exact, q)
            est = sk.quantile(q)
            # Relative VALUE bound (rank selection is exact, the
            # in-bucket representative is alpha-accurate); near zero
            # the bound degrades to the bucket span around zero, so
            # allow a small absolute epsilon there.
            assert abs(est - e) <= alpha * abs(e) + 1e-11, \
                f"{name}: q={q} exact={e} est={est}"


def test_quantile_extremes_and_ties_exact():
    sk = QuantileSketch()
    data = np.array([5.0, -3.0, 5.0, 5.0, 8.5])
    sk.update(data)
    assert sk.quantile(0.0) == -3.0
    assert sk.quantile(1.0) == 8.5
    ties = QuantileSketch()
    ties.update(np.full(100, 4.25))
    for q in (0.0, 0.3, 0.5, 1.0):
        assert ties.quantile(q) == pytest.approx(4.25, rel=0.01)


def test_empty_and_single_element_sketches():
    q = QuantileSketch()
    assert q.count == 0 and q.quantile(0.5) is None
    assert q.summary()["count"] == 0
    m = MomentsSketch()
    assert m.mean is None and m.variance is None
    t = TopKSketch(4)
    assert t.items() == [] and t.error_bound() == 0
    # single element: every quantile is the element, exactly
    q.update([3.7])
    for p in (0.0, 0.5, 1.0):
        assert q.quantile(p) == 3.7
    m.update([3.7])
    assert m.mean == 3.7 and m.variance == 0.0 and m.nnz == 1
    # empty UPDATE payloads are no-ops
    q.update(np.zeros(0))
    m.update([])
    assert q.count == 1 and m.count == 1
    # round-trip through state keeps everything
    assert sketch_from_state(q.state()).serialize() == q.serialize()
    assert sketch_from_state(m.state()).serialize() == m.serialize()


def test_non_finite_rejected():
    for sk in (QuantileSketch(), MomentsSketch()):
        with pytest.raises(ValueError):
            sk.update([1.0, float("nan")])
        with pytest.raises(ValueError):
            sk.update([float("inf")])


@pytest.mark.parametrize("cls", [QuantileSketch, MomentsSketch])
def test_merge_tree_bitwise_determinism(cls):
    """The core mergeability contract: ANY merge tree over the same
    sub-sketches — left fold, right fold, balanced, permuted — yields
    bitwise-identical serialized state, equal to single-stream
    ingestion of the same update sequence."""
    rng = np.random.default_rng(3)
    data = _adversarial_streams(rng)["shuffled"]
    chunks = np.array_split(data, 7)

    def build(chunk):
        s = cls()
        s.update(chunk)
        return s

    # single stream, one update per chunk (the monitor's shape)
    single = cls()
    for c in chunks:
        single.update(c)

    left = build(chunks[0])
    for c in chunks[1:]:
        left.merge(build(c))

    right = build(chunks[-1])
    for c in chunks[-2::-1]:
        # right-leaning tree: merge accumulated INTO each new left node
        node = build(c)
        node.merge(right)
        right = node

    parts = [build(c) for c in chunks]
    t1 = parts[3].merge(parts[5])
    t2 = parts[1].merge(parts[0]).merge(parts[6])
    balanced = t1.merge(t2).merge(parts[2].merge(parts[4]))

    blobs = {s.serialize() for s in (single, left, right, balanced)}
    assert len(blobs) == 1
    # and the canonical digest matches a state round-trip
    restored = sketch_from_state(single.state())
    assert restored.serialize() == single.serialize()


def test_moments_adversarial_magnitudes_exact():
    """Float reassociation is exactly what the Fraction accumulator
    removes: 1e16 + 1 - 1e16 ACROSS updates keeps the 1.0 in every
    merge order, where float partial sums would lose it in most orders.
    (Within one update the contribution is one correctly-rounded fsum —
    rounding there is deterministic, not reassociation.)"""
    payloads = [[1e16], [1.0], [-1e16], [2.5], [1e-30], [-2.5]]
    import itertools

    blobs = set()
    means = set()
    for perm in itertools.permutations(range(len(payloads))):
        m = MomentsSketch()
        for i in perm:
            part = MomentsSketch()
            part.update(payloads[i])
            m.merge(part)
        blobs.add(m.serialize())
        means.add(m.mean)
    assert len(blobs) == 1
    (mean,) = means
    assert mean == pytest.approx((1.0 + 1e-30) / 6)
    m = MomentsSketch()
    m.update(np.array([1.0, 2.0, 3.0, 4.0]))
    assert m.mean == 2.5
    assert m.variance == pytest.approx(np.var([1, 2, 3, 4], ddof=1))
    assert m.nnz == 4 and m.count == 4


def test_quantile_merge_accuracy_matches_single_pass():
    rng = np.random.default_rng(11)
    data = rng.lognormal(0, 2, 20_000)
    merged = QuantileSketch()
    for chunk in np.array_split(data, 13):
        part = QuantileSketch()
        part.update(chunk)
        merged.merge(part)
    exact = np.sort(data)
    for q in (0.1, 0.5, 0.9, 0.99):
        e = _exact_quantile(exact, q)
        assert abs(merged.quantile(q) - e) <= 0.01 * abs(e)


def test_merge_rejects_mismatched_grids():
    with pytest.raises(ValueError):
        QuantileSketch(0.01).merge(QuantileSketch(0.02))
    with pytest.raises(ValueError):
        TopKSketch(4).merge(TopKSketch(8))


def test_heavy_hitter_guarantees():
    """Misra-Gries: every key with true frequency > n/(k+1) survives;
    stored counts undercount by at most error_bound() <= n/(k+1)."""
    rng = np.random.default_rng(5)
    k = 8
    true = {"whale": 500, "shark": 300, "tuna": 150}
    noise = [f"minnow{i}" for i in range(400)]
    stream = sum(([key] * c for key, c in true.items()), []) + noise
    rng.shuffle(stream)
    tk = TopKSketch(k)
    for chunk in np.array_split(np.asarray(stream), 11):
        tk.update(chunk)
    n = tk.total
    assert n == len(stream)
    assert tk.error_bound() <= n / (k + 1)
    stored = dict(tk.items())
    for key, c in true.items():
        if c > n / (k + 1):
            assert key in stored, key
            assert 0 <= c - stored[key] <= tk.error_bound()
    # merge keeps the combined guarantee
    a, b = TopKSketch(k), TopKSketch(k)
    a.update(np.asarray(stream[: len(stream) // 2]))
    b.update(np.asarray(stream[len(stream) // 2:]))
    a.merge(b)
    assert a.total == n
    assert a.error_bound() <= n / (k + 1) + n / (k + 1)
    merged = dict(a.items())
    assert "whale" in merged
    assert 0 <= true["whale"] - merged["whale"] <= a.error_bound()


def test_topk_fixed_order_determinism():
    rng = np.random.default_rng(9)
    keys = rng.choice([f"e{i}" for i in range(50)], 3000)
    chunks = np.array_split(keys, 7)

    def run():
        t = TopKSketch(6)
        for c in chunks:
            t.update(c)
        return t.serialize()

    assert run() == run()


def test_drift_scores():
    rng = np.random.default_rng(2)
    ref = QuantileSketch(0.02)
    ref.update(rng.normal(0, 1, 20_000))
    same = QuantileSketch(0.02)
    same.update(rng.normal(0, 1, 20_000))
    shifted = QuantileSketch(0.02)
    shifted.update(rng.normal(2.0, 1, 20_000))
    p_same, p_shift = psi(ref, same), psi(ref, shifted)
    assert p_same < 0.05 < p_shift
    assert p_shift > 0.25  # the conventional "major shift" threshold
    k_same, k_shift = ks(ref, same), ks(ref, shifted)
    assert 0.0 <= k_same < 0.05
    assert 0.2 < k_shift <= 1.0
    # identical sketches: exactly zero drift
    assert psi(ref, ref) == pytest.approx(0.0, abs=1e-12)
    assert ks(ref, ref) == 0.0
    # empty side: nothing to judge
    assert psi(ref, QuantileSketch(0.02)) is None
    assert ks(QuantileSketch(0.02), ref) is None
    # state-dict operands (the model-artifact form) work identically
    assert psi(ref.state(), shifted.state()) == pytest.approx(p_shift)


def test_convergence_ring_bounded_and_threadsafe_snapshot():
    ring = ConvergenceRing(capacity=8)
    for i in range(20):
        ring.append(i, 100.0 - i, 1.0 / (i + 1), 0.5)
    snap = ring.snapshot()
    assert snap["recorded"] == 20
    assert len(snap["tail"]) == 8
    assert snap["tail"][-1] == {"iteration": 19, "value": 81.0,
                                "grad_norm": 1.0 / 20, "step": 0.5}
    assert snap["tail"][0]["iteration"] == 12  # oldest retained
    with pytest.raises(ValueError):
        ConvergenceRing(capacity=0)
