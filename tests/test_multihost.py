"""Two-process jax.distributed test on localhost (VERDICT Missing #4).

The reference proves its multi-node paths with Spark local[4]
(photon-test-utils/.../SparkTestUtils.scala:55-70) — threads standing in
for executors. The analog here is stronger: two REAL processes, each with
2 virtual CPU devices, joined through jax.distributed's coordination
service into one 4-device mesh, exercising initialize_multihost's
coordinator path, cross-process array assembly, and a cross-host psum.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

WORKER = Path(__file__).parent / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_multihost():
    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = dict(
            os.environ,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            PYTHONPATH=str(WORKER.parent.parent),
        )
        # The conftest's own env (single-process 8-device) must not leak in.
        env.pop("XLA_FLAGS", None)
        env.pop("PHOTON_ML_TPU_TEST_F32", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, (
            f"worker {pid} failed (rc={p.returncode}):\n{out}")
        assert f"MULTIHOST_OK process={pid} total=28.0" in out, out
