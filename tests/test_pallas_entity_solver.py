"""Parity: the fused Pallas per-entity solver vs the vmapped jnp path.

Runs the kernel in interpreter mode (no TPU needed) on the same buckets
the random-effect coordinate builds, and checks solutions match the
portable solver to solver tolerance.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tests.conftest import gold
from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.pallas_entity_solver import pallas_entity_lbfgs
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optimization.solver import solve_glm
from photon_ml_tpu.types import TaskType


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _bucket(rng, e, r, d, dtype):
    x = rng.normal(0, 1, (e, r, d)).astype(dtype)
    x[:, :, 0] = 1.0
    w_true = rng.normal(0, 0.5, (e, d))
    z = np.einsum("erd,ed->er", x, w_true)
    y = (rng.random((e, r)) < 1 / (1 + np.exp(-z))).astype(dtype)
    off = rng.normal(0, 0.1, (e, r)).astype(dtype)
    w = np.ones((e, r), dtype)
    return x, y, off, w


@pytest.mark.parametrize("task", [TaskType.LOGISTIC_REGRESSION,
                                  TaskType.POISSON_REGRESSION])
def test_pallas_solver_matches_vmapped(rng, task):
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 37, 6, 5  # e deliberately not a multiple of 128 (pad lanes)
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    if task == TaskType.POISSON_REGRESSION:
        y = rng.poisson(2.0, (e, r)).astype(dtype)
    loss = loss_for_task(task)
    obj = GLMObjective(loss)
    cfg = GLMOptimizationConfiguration(
        max_iterations=40, tolerance=1e-8, regularization_weight=0.7,
        regularization_context=RegularizationContext(RegularizationType.L2))
    coef0 = np.zeros((e, d), dtype)

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), jnp.asarray(coef0), 0.7,
        max_iter=40, tol=1e-8, interpret=True)

    def fit_one(c0, xe, ye, oe, we):
        return solve_glm(obj, GLMBatch(DenseFeatures(xe), ye, oe, we),
                         cfg, c0)

    res_v = jax.vmap(fit_one)(jnp.asarray(coef0), jnp.asarray(x),
                              jnp.asarray(y), jnp.asarray(off),
                              jnp.asarray(w))

    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-8, f32_floor=1e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-5, f32_floor=5e-3))
    assert res_k.x.shape == (e, d)
    # Both paths agree on which entities converged.
    assert np.array_equal(np.asarray(res_k.converged),
                          np.asarray(res_v.converged))


def test_pallas_solver_zero_weight_entities(rng):
    """All-zero-weight (padding-style) entities converge immediately at
    coef0 and report GRADIENT_CONVERGED with 0 iterations."""
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 5, 4, 3
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    w[2] = 0.0
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    res = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), jnp.zeros((e, d), dtype), 0.0,
        max_iter=20, tol=1e-7, interpret=True)
    assert int(res.iterations[2]) == 0
    np.testing.assert_array_equal(np.asarray(res.x[2]), 0.0)


def test_solve_block_routes_through_kernel(monkeypatch, rng):
    """PHOTON_ML_TPU_PALLAS_INTERPRET=1 routes _solve_block through the
    fused kernel on any backend (interpreter mode) — the end-to-end drive
    of the routing layer without TPU hardware. The kernel path is
    distinguishable by its untracked histories (value_history is None)."""
    from photon_ml_tpu.algorithm.coordinates import _solve_block
    from photon_ml_tpu.data.random_effect import EntityBlock
    from photon_ml_tpu.ops.glm_objective import GLMObjective as Obj

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 23, 5, 4
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    block = EntityBlock(
        x=jnp.asarray(x), labels=jnp.asarray(y), offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
        row_ids=np.zeros((e, r), np.int32),
        feat_idx=np.broadcast_to(np.arange(d, dtype=np.int32), (e, d)))
    obj = Obj(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    c0 = jnp.zeros((e, d), dtype)

    def cfg(tol):
        # distinct tolerances force distinct jit cache entries — the
        # routing env vars are read at trace time
        return GLMOptimizationConfiguration(
            max_iterations=25, tolerance=tol, regularization_weight=0.4,
            regularization_context=RegularizationContext(
                RegularizationType.L2))

    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    res_k = _solve_block(obj, cfg(1e-7), block, None, c0)
    assert res_k.value_history is None  # kernel path ran
    monkeypatch.delenv("PHOTON_ML_TPU_PALLAS_INTERPRET")
    monkeypatch.setenv("PHOTON_ML_TPU_NO_PALLAS", "1")  # backend-independent
    res_v = _solve_block(obj, cfg(1.001e-7), block, None, c0)
    assert res_v.value_history is not None  # vmapped path ran
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-6, f32_floor=1e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-5, f32_floor=5e-3))


def test_pallas_solver_deep_backtracking_tail(rng):
    """Force the tiered line search past tier 1 (8 candidates): Poisson
    with large-scale features makes early trial margins overflow exp, so
    the first finite+Armijo step sits deep in the backtracking schedule.
    The kernel must agree with the vmapped solver (same candidate set)."""
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 9, 8, 3
    x = (rng.normal(0, 1, (e, r, d)) * 30.0).astype(dtype)
    y = rng.poisson(3.0, (e, r)).astype(dtype)
    off = np.zeros((e, r), dtype)
    w = np.ones((e, r), dtype)
    loss = loss_for_task(TaskType.POISSON_REGRESSION)
    obj = GLMObjective(loss)
    cfg = GLMOptimizationConfiguration(
        max_iterations=30, tolerance=1e-8, regularization_weight=0.1,
        regularization_context=RegularizationContext(RegularizationType.L2))

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), jnp.zeros((e, d), dtype), 0.1,
        max_iter=30, tol=1e-8, interpret=True)

    def fit_one(c0, xe, ye, oe, we):
        return solve_glm(obj, GLMBatch(DenseFeatures(xe), ye, oe, we),
                         cfg, c0)

    res_v = jax.vmap(fit_one)(jnp.zeros((e, d), dtype), jnp.asarray(x),
                              jnp.asarray(y), jnp.asarray(off),
                              jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-7, f32_floor=2e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-4, f32_floor=1e-2))


def test_factored_latent_solve_routes_through_kernel(monkeypatch, rng):
    """The factored coordinate's latent (gamma) bucket solve routes
    through the kernel too — drive _solve_factored_block both ways and
    check solution parity (the projection einsum feeds the kernel a
    [E, r, k] latent design)."""
    from photon_ml_tpu.algorithm.coordinates import _solve_factored_block
    from photon_ml_tpu.data.random_effect import EntityBlock
    from photon_ml_tpu.ops.glm_objective import GLMObjective as Obj

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d, k = 17, 6, 5, 2
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    block = EntityBlock(
        x=jnp.asarray(x), labels=jnp.asarray(y), offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
        row_ids=np.zeros((e, r), np.int32),
        feat_idx=np.broadcast_to(np.arange(d, dtype=np.int32), (e, d)))
    B = jnp.asarray(rng.normal(0, 0.5, (k, d)).astype(dtype))
    g0 = jnp.zeros((e, k), dtype)
    obj = Obj(loss_for_task(TaskType.LOGISTIC_REGRESSION))

    def cfg(tol):
        return GLMOptimizationConfiguration(
            max_iterations=20, tolerance=tol, regularization_weight=0.3,
            regularization_context=RegularizationContext(
                RegularizationType.L2))

    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    res_k = _solve_factored_block(obj, cfg(1e-7), block, B, None, g0, d)
    assert res_k.value_history is None  # kernel path ran
    monkeypatch.delenv("PHOTON_ML_TPU_PALLAS_INTERPRET")
    monkeypatch.setenv("PHOTON_ML_TPU_NO_PALLAS", "1")
    res_v = _solve_factored_block(obj, cfg(1.001e-7), block, B, None, g0, d)
    assert res_v.value_history is not None
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-6, f32_floor=1e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-5, f32_floor=5e-3))


@pytest.mark.parametrize("e,r,d", [(1, 1, 1), (1, 3, 2), (129, 2, 1),
                                   (128, 4, 7), (40, 1, 5)])
def test_pallas_solver_edge_shapes(rng, e, r, d):
    """Degenerate shapes: single entity, single row, single feature, and
    entity counts straddling the 128-lane boundary."""
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    obj = GLMObjective(loss)
    cfg = GLMOptimizationConfiguration(
        max_iterations=15, tolerance=1e-7, regularization_weight=0.6,
        regularization_context=RegularizationContext(RegularizationType.L2))
    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), jnp.zeros((e, d), dtype), 0.6,
        max_iter=15, tol=1e-7, interpret=True)

    def fit_one(c0, xe, ye, oe, we):
        return solve_glm(obj, GLMBatch(DenseFeatures(xe), ye, oe, we),
                         cfg, c0)

    res_v = jax.vmap(fit_one)(jnp.zeros((e, d), dtype), jnp.asarray(x),
                              jnp.asarray(y), jnp.asarray(off),
                              jnp.asarray(w))
    assert res_k.x.shape == (e, d)
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-7, f32_floor=1e-4))


@pytest.mark.slow
def test_pallas_owlqn_matches_vmapped(rng):
    """Elastic-net (OWL-QN) kernel mode vs the vmapped minimize_owlqn
    path through solve_glm — values, coefficients, and the SPARSITY
    pattern (which coordinates are exactly zero) must agree."""
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 31, 8, 6
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    obj = GLMObjective(loss)
    lam, alpha = 1.5, 0.5  # strong l1 so real zeros appear
    cfg = GLMOptimizationConfiguration(
        max_iterations=60, tolerance=1e-9, regularization_weight=lam,
        regularization_context=RegularizationContext(
            RegularizationType.ELASTIC_NET, alpha))

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), jnp.zeros((e, d), dtype),
        (1 - alpha) * lam, alpha * lam,
        max_iter=60, tol=1e-9, mode="owlqn", interpret=True)

    def fit_one(c0, xe, ye, oe, we):
        return solve_glm(obj, GLMBatch(DenseFeatures(xe), ye, oe, we),
                         cfg, c0)

    res_v = jax.vmap(fit_one)(jnp.zeros((e, d), dtype), jnp.asarray(x),
                              jnp.asarray(y), jnp.asarray(off),
                              jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-7, f32_floor=1e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-6, f32_floor=5e-3))
    # exact-zero sets agree (the orthant method's signature behavior)
    zk = np.asarray(res_k.x) == 0.0
    zv = np.asarray(res_v.x) == 0.0
    assert zk.any()  # the l1 weight is strong enough to produce zeros
    assert np.array_equal(zk, zv)


@pytest.mark.slow
def test_solve_block_routes_elastic_net_through_kernel(monkeypatch, rng):
    """_solve_block routes ELASTIC_NET configs to the kernel's OWL-QN
    mode (previously an automatic fallback to the vmapped path)."""
    from photon_ml_tpu.algorithm.coordinates import _solve_block
    from photon_ml_tpu.data.random_effect import EntityBlock

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 19, 5, 4
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    block = EntityBlock(
        x=jnp.asarray(x), labels=jnp.asarray(y), offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
        row_ids=np.zeros((e, r), np.int32),
        feat_idx=np.broadcast_to(np.arange(d, dtype=np.int32), (e, d)))
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    c0 = jnp.zeros((e, d), dtype)

    def cfg(tol):
        return GLMOptimizationConfiguration(
            max_iterations=40, tolerance=tol, regularization_weight=0.8,
            regularization_context=RegularizationContext(
                RegularizationType.ELASTIC_NET, 0.5))

    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    res_k = _solve_block(obj, cfg(1e-8), block, None, c0)
    assert res_k.value_history is None  # kernel path ran
    monkeypatch.delenv("PHOTON_ML_TPU_PALLAS_INTERPRET")
    monkeypatch.setenv("PHOTON_ML_TPU_NO_PALLAS", "1")
    res_v = _solve_block(obj, cfg(1.001e-8), block, None, c0)
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-6, f32_floor=1e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-5, f32_floor=5e-3))


@pytest.mark.parametrize("task", [TaskType.LOGISTIC_REGRESSION,
                                  TaskType.POISSON_REGRESSION,
                                  TaskType.LINEAR_REGRESSION])
def test_pallas_tron_matches_vmapped(rng, task):
    """TRON kernel mode vs the vmapped minimize_tron path through
    solve_glm (LIBLINEAR trust-region rules, truncated CG)."""
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 29, 7, 5
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    if task == TaskType.POISSON_REGRESSION:
        y = rng.poisson(2.0, (e, r)).astype(dtype)
    elif task == TaskType.LINEAR_REGRESSION:
        y = rng.normal(0, 1, (e, r)).astype(dtype)
    loss = loss_for_task(task)
    obj = GLMObjective(loss)
    cfg = GLMOptimizationConfiguration(
        max_iterations=15, tolerance=1e-7, regularization_weight=0.5,
        regularization_context=RegularizationContext(RegularizationType.L2),
        optimizer_type=OptimizerType.TRON)

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), jnp.zeros((e, d), dtype), 0.5,
        max_iter=15, tol=1e-7, mode="tron", interpret=True)

    def fit_one(c0, xe, ye, oe, we):
        return solve_glm(obj, GLMBatch(DenseFeatures(xe), ye, oe, we),
                         cfg, c0)

    res_v = jax.vmap(fit_one)(jnp.zeros((e, d), dtype), jnp.asarray(x),
                              jnp.asarray(y), jnp.asarray(off),
                              jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-7, f32_floor=2e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-4, f32_floor=1e-2))


@pytest.mark.slow
def test_solve_block_routes_tron_through_kernel(monkeypatch, rng):
    """TRON random-effect configs reach the kernel; once-differentiable
    losses keep the vmapped fallback (which raises solve_glm's error)."""
    from photon_ml_tpu.algorithm.coordinates import (
        _solve_block,
        _use_pallas_entity_solver,
    )
    from photon_ml_tpu.data.random_effect import EntityBlock

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 13, 4, 3
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    block = EntityBlock(
        x=jnp.asarray(x), labels=jnp.asarray(y), offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
        row_ids=np.zeros((e, r), np.int32),
        feat_idx=np.broadcast_to(np.arange(d, dtype=np.int32), (e, d)))
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    c0 = jnp.zeros((e, d), dtype)

    def cfg(tol):
        return GLMOptimizationConfiguration(
            max_iterations=12, tolerance=tol, regularization_weight=0.4,
            regularization_context=RegularizationContext(
                RegularizationType.L2),
            optimizer_type=OptimizerType.TRON)

    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    res_k = _solve_block(obj, cfg(1e-7), block, None, c0)
    assert res_k.value_history is None  # kernel path ran
    monkeypatch.delenv("PHOTON_ML_TPU_PALLAS_INTERPRET")
    monkeypatch.setenv("PHOTON_ML_TPU_NO_PALLAS", "1")
    res_v = _solve_block(obj, cfg(1.001e-7), block, None, c0)
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-6, f32_floor=1e-4))

    # Guard: TRON + once-differentiable loss never routes to the kernel.
    hinge_obj = GLMObjective(
        loss_for_task(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM))
    assert not _use_pallas_entity_solver(hinge_obj, cfg(1e-7), block.x,
                                         sharded=False)


@pytest.mark.parametrize("mode", ["tron", "owlqn"])
@pytest.mark.slow
def test_pallas_solver_overflow_trials_stay_finite(rng, mode):
    """Rejected trial steps whose margins overflow exp must not poison
    the retained iterate (the arithmetic keep-old select computes
    b + m*(a-b), and 0*inf is NaN): Poisson with huge feature scale
    forces non-finite trial values; results must stay finite and match
    the vmapped solver."""
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 7, 6, 3
    x = (rng.normal(0, 1, (e, r, d)) * 300.0).astype(dtype)
    y = rng.poisson(3.0, (e, r)).astype(dtype)
    off = np.zeros((e, r), dtype)
    w = np.ones((e, r), dtype)
    loss = loss_for_task(TaskType.POISSON_REGRESSION)
    obj = GLMObjective(loss)
    reg = (RegularizationContext(RegularizationType.L2) if mode == "tron"
           else RegularizationContext(RegularizationType.ELASTIC_NET, 0.5))
    cfg = GLMOptimizationConfiguration(
        max_iterations=12, tolerance=1e-7, regularization_weight=0.5,
        regularization_context=reg,
        optimizer_type=(OptimizerType.TRON if mode == "tron"
                        else OptimizerType.LBFGS))
    l1 = 0.25 if mode == "owlqn" else 0.0
    l2 = 0.5 if mode == "tron" else 0.25

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), jnp.zeros((e, d), dtype), l2, l1,
        max_iter=12, tol=1e-7, mode=mode, interpret=True)
    assert np.isfinite(np.asarray(res_k.x)).all()
    assert np.isfinite(np.asarray(res_k.value)).all()

    def fit_one(c0, xe, ye, oe, we):
        return solve_glm(obj, GLMBatch(DenseFeatures(xe), ye, oe, we),
                         cfg, c0)

    res_v = jax.vmap(fit_one)(jnp.zeros((e, d), dtype), jnp.asarray(x),
                              jnp.asarray(y), jnp.asarray(off),
                              jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-6, f32_floor=2e-4))


def test_kernel_composes_with_entity_sharding(monkeypatch, rng):
    """Mesh-sharded buckets run the kernel PER DEVICE via shard_map (each
    device solves its own entity shard); results match the unsharded
    kernel for real entities and padding entities stay zero."""
    from photon_ml_tpu.algorithm.coordinates import _solve_block
    from photon_ml_tpu.data.random_effect import EntityBlock
    from photon_ml_tpu.parallel import make_mesh, shard_block

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 21, 5, 4  # pads to 24 entities over 8 devices
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    block = EntityBlock(
        x=jnp.asarray(x), labels=jnp.asarray(y), offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
        row_ids=np.zeros((e, r), np.int32),
        feat_idx=np.broadcast_to(np.arange(d, dtype=np.int32), (e, d)))
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))

    def cfg(tol):
        return GLMOptimizationConfiguration(
            max_iterations=25, tolerance=tol, regularization_weight=0.4,
            regularization_context=RegularizationContext(
                RegularizationType.L2))

    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    plain = _solve_block(obj, cfg(1e-8), block, None,
                         jnp.zeros((e, d), dtype))
    assert plain.value_history is None  # kernel path

    mesh = make_mesh()
    sblock = shard_block(block, mesh, sentinel_row=1000)
    ep = sblock.num_entities
    assert ep == 24
    sharded = _solve_block(obj, cfg(1.001e-8), sblock, None,
                           jnp.zeros((ep, d), dtype),
                           sharded=True, mesh=mesh)
    assert sharded.value_history is None  # kernel ran under shard_map
    np.testing.assert_allclose(np.asarray(sharded.x[:e]),
                               np.asarray(plain.x),
                               atol=gold(1e-6, f32_floor=5e-3))
    np.testing.assert_allclose(np.asarray(sharded.value[:e]),
                               np.asarray(plain.value),
                               rtol=gold(1e-7, f32_floor=1e-4))
    # padding entities (weight 0) converge instantly at zero
    np.testing.assert_array_equal(np.asarray(sharded.x[e:]), 0.0)
    np.testing.assert_array_equal(np.asarray(sharded.iterations[e:]), 0)


def test_factored_kernel_composes_with_entity_sharding(monkeypatch, rng):
    """The factored-latent kernel also composes with entity sharding via
    shard_map (B replicated, latent designs sharded)."""
    from photon_ml_tpu.algorithm.coordinates import _solve_factored_block
    from photon_ml_tpu.data.random_effect import EntityBlock
    from photon_ml_tpu.parallel import make_mesh, shard_block

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d, k = 13, 4, 5, 2  # pads to 16 entities over 8 devices
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    block = EntityBlock(
        x=jnp.asarray(x), labels=jnp.asarray(y), offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
        row_ids=np.zeros((e, r), np.int32),
        feat_idx=np.broadcast_to(np.arange(d, dtype=np.int32), (e, d)))
    B = jnp.asarray(rng.normal(0, 0.5, (k, d)).astype(dtype))
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))

    def cfg(tol):
        return GLMOptimizationConfiguration(
            max_iterations=20, tolerance=tol, regularization_weight=0.3,
            regularization_context=RegularizationContext(
                RegularizationType.L2))

    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    plain = _solve_factored_block(obj, cfg(1e-8), block, B, None,
                                  jnp.zeros((e, k), dtype), d)
    assert plain.value_history is None

    mesh = make_mesh()
    sblock = shard_block(block, mesh, sentinel_row=1000)
    ep = sblock.num_entities
    sharded = _solve_factored_block(obj, cfg(1.001e-8), sblock, B, None,
                                    jnp.zeros((ep, k), dtype), d,
                                    sharded=True, mesh=mesh)
    assert sharded.value_history is None
    np.testing.assert_allclose(np.asarray(sharded.x[:e]),
                               np.asarray(plain.x),
                               atol=gold(1e-6, f32_floor=5e-3))
    np.testing.assert_array_equal(np.asarray(sharded.x[e:]), 0.0)


def test_vmem_oversize_bucket_keeps_vmapped_path(monkeypatch, rng):
    """Buckets whose kernel working set would exceed the VMEM budget
    route to the vmapped solver even when the kernel is forced on."""
    from photon_ml_tpu.algorithm.coordinates import _use_pallas_entity_solver
    from photon_ml_tpu.ops.glm_objective import GLMObjective as Obj

    obj = Obj(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    cfg = GLMOptimizationConfiguration(
        max_iterations=10, tolerance=1e-6, regularization_weight=0.5,
        regularization_context=RegularizationContext(RegularizationType.L2))
    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    small = jax.ShapeDtypeStruct((100, 8, 16), jnp.float32)
    big = jax.ShapeDtypeStruct((100, 400, 128), jnp.float32)  # ~26 MB tile
    assert _use_pallas_entity_solver(obj, cfg, small, sharded=False)
    assert not _use_pallas_entity_solver(obj, cfg, big, sharded=False)
