"""Parity: normalization and box constraints folded into the fused
Pallas entity kernel vs the vmapped host path.

Closes VERDICT r3 weak #4 — STANDARDIZATION
(NormalizationContext.scala:38-83) and box constraints
(OptimizationUtils.scala:53) are first-class reference features on
random-effect problems (RandomEffectOptimizationProblem.scala:105-125);
they must keep the kernel path, not silently shed it. All kernel runs
here use interpreter mode (no TPU needed).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tests.conftest import gold
from photon_ml_tpu.data.normalization import (
    NormalizationContext,
    gather_normalization,
    gathered_to_normalized_space,
    gathered_to_original_space,
)
from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.pallas_entity_solver import pallas_entity_lbfgs
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optimization.solver import solve_glm
from photon_ml_tpu.types import TaskType


@pytest.fixture
def rng():
    return np.random.default_rng(23)


def _bucket(rng, e, r, d, dtype, scale=None):
    x = rng.normal(0, 1, (e, r, d)).astype(dtype)
    if scale is not None:  # badly-scaled columns: what normalization fixes
        x *= scale[None, None, :]
    x[:, :, 0] = 1.0  # intercept column
    w_true = rng.normal(0, 0.5, (e, d))
    z = np.einsum("erd,ed->er", x / (scale[None, None, :] if scale is not None
                                     else 1.0), w_true)
    y = (rng.random((e, r)) < 1 / (1 + np.exp(-z))).astype(dtype)
    off = rng.normal(0, 0.1, (e, r)).astype(dtype)
    w = np.ones((e, r), dtype)
    return x, y, off, w


def _standardization_arrays(rng, e, r, d, x, dtype):
    """Per-entity STANDARDIZATION-like factor/shift arrays (intercept
    column 0 untouched: factor 1, shift 0)."""
    fac = 1.0 / np.maximum(x.std(axis=(0, 1)), 0.2)
    shf = x.mean(axis=(0, 1))
    fac[0], shf[0] = 1.0, 0.0
    factors = np.tile(fac, (e, 1)).astype(dtype)
    shifts = np.tile(shf, (e, 1)).astype(dtype)
    return jnp.asarray(factors), jnp.asarray(shifts)


def _vmapped(obj, cfg, x, y, off, w, coef0, factors=None, shifts=None,
             lb=None, ub=None):
    def fit_one(c0, xe, ye, oe, we, fe, se, le, ue):
        if se is not None:
            xe = xe - se[None, :]
        if fe is not None:
            xe = xe * fe[None, :]
        return solve_glm(obj, GLMBatch(DenseFeatures(xe), ye, oe, we),
                         cfg, c0, le, ue)

    return jax.vmap(fit_one)(coef0, x, y, off, w, factors, shifts, lb, ub)


@pytest.mark.parametrize("mode,opt,l1", [
    ("lbfgs", OptimizerType.LBFGS, 0.0),
    ("owlqn", OptimizerType.LBFGS, 0.3),
    ("tron", OptimizerType.TRON, 0.0),
])
@pytest.mark.slow
def test_kernel_normalization_matches_vmapped(rng, mode, opt, l1):
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 29, 6, 5
    scale = np.array([1.0, 10.0, 0.1, 5.0, 0.5])
    x, y, off, w = _bucket(rng, e, r, d, dtype, scale=scale)
    factors, shifts = _standardization_arrays(rng, e, r, d, x, dtype)
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    obj = GLMObjective(loss)
    reg = (RegularizationContext(RegularizationType.ELASTIC_NET, 0.5)
           if l1 > 0 else RegularizationContext(RegularizationType.L2))
    lam = 0.8
    cfg = GLMOptimizationConfiguration(
        max_iterations=40, tolerance=1e-8, regularization_weight=lam,
        regularization_context=reg, optimizer_type=opt)
    l1w, l2w = reg.l1_weight(lam), reg.l2_weight(lam)
    coef0 = jnp.zeros((e, d), dtype)

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), coef0, l2w, l1w, factors=factors, shifts=shifts,
        max_iter=40, tol=1e-8, mode=mode, interpret=True)
    res_v = _vmapped(obj, cfg, jnp.asarray(x), jnp.asarray(y),
                     jnp.asarray(off), jnp.asarray(w), coef0,
                     factors=factors, shifts=shifts)

    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-8, f32_floor=2e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-5, f32_floor=8e-3))
    # Normalization actually did something: the normalized solve from a
    # zero start differs from an un-normalized one.
    res_raw = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), coef0, l2w, l1w, max_iter=40, tol=1e-8, mode=mode,
        interpret=True)
    assert not np.allclose(np.asarray(res_k.x), np.asarray(res_raw.x),
                           atol=1e-4)


@pytest.mark.parametrize("mode,opt", [
    ("lbfgs", OptimizerType.LBFGS),
    ("tron", OptimizerType.TRON),
])
def test_kernel_bounds_match_vmapped(rng, mode, opt):
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 33, 6, 5
    x, y, off, w = _bucket(rng, e, r, d, dtype)
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    obj = GLMObjective(loss)
    cfg = GLMOptimizationConfiguration(
        max_iterations=40, tolerance=1e-8, regularization_weight=0.5,
        regularization_context=RegularizationContext(RegularizationType.L2),
        optimizer_type=opt)
    coef0 = jnp.zeros((e, d), dtype)
    # Tight asymmetric box: several coordinates must end up clamped.
    lb = jnp.full((e, d), -0.05, dtype)
    ub = jnp.full((e, d), 0.12, dtype)

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), coef0, 0.5, lower=lb, upper=ub,
        max_iter=40, tol=1e-8, mode=mode, interpret=True)
    res_v = _vmapped(obj, cfg, jnp.asarray(x), jnp.asarray(y),
                     jnp.asarray(off), jnp.asarray(w), coef0,
                     lb=lb, ub=ub)

    xk = np.asarray(res_k.x)
    assert (xk >= -0.05 - 1e-6).all() and (xk <= 0.12 + 1e-6).all()
    assert (np.isclose(xk, -0.05, atol=1e-6) |
            np.isclose(xk, 0.12, atol=1e-6)).any(), "box never active"
    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-7, f32_floor=2e-4))
    np.testing.assert_allclose(xk, np.asarray(res_v.x),
                               atol=gold(1e-5, f32_floor=8e-3))


@pytest.mark.parametrize("mode,opt", [
    ("lbfgs", OptimizerType.LBFGS),
    ("tron", OptimizerType.TRON),
])
def test_kernel_bounds_with_normalization(rng, mode, opt):
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 17, 5, 4
    scale = np.array([1.0, 8.0, 0.2, 3.0])
    x, y, off, w = _bucket(rng, e, r, d, dtype, scale=scale)
    factors, shifts = _standardization_arrays(rng, e, r, d, x, dtype)
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    obj = GLMObjective(loss)
    cfg = GLMOptimizationConfiguration(
        max_iterations=40, tolerance=1e-8, regularization_weight=0.5,
        regularization_context=RegularizationContext(RegularizationType.L2),
        optimizer_type=opt)
    coef0 = jnp.zeros((e, d), dtype)
    lb = jnp.full((e, d), -0.08, dtype)
    ub = jnp.full((e, d), 0.15, dtype)

    res_k = pallas_entity_lbfgs(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
        jnp.asarray(w), coef0, 0.5, factors=factors, shifts=shifts,
        lower=lb, upper=ub, max_iter=40, tol=1e-8, mode=mode,
        interpret=True)
    res_v = _vmapped(obj, cfg, jnp.asarray(x), jnp.asarray(y),
                     jnp.asarray(off), jnp.asarray(w), coef0,
                     factors=factors, shifts=shifts, lb=lb, ub=ub)

    np.testing.assert_allclose(np.asarray(res_k.value),
                               np.asarray(res_v.value),
                               rtol=gold(1e-7, f32_floor=2e-4))
    np.testing.assert_allclose(np.asarray(res_k.x), np.asarray(res_v.x),
                               atol=gold(1e-5, f32_floor=8e-3))


def test_bounds_reject_owlqn_mode():
    """L1 + box constraints stays rejected (matching solve_glm); TRON +
    bounds is now a supported kernel mode (projected trust region,
    TRON.scala:228)."""
    e, r, d = 4, 3, 3
    z = jnp.zeros((e, r, d))
    zr = jnp.zeros((e, r))
    zc = jnp.zeros((e, d))
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    with pytest.raises(ValueError, match="L1"):
        pallas_entity_lbfgs(loss, z, zr, zr, zr, zc, 0.1, 0.2,
                            lower=jnp.full((e, d), -1.0), mode="owlqn",
                            interpret=True)


def test_gathered_transforms_round_trip(rng):
    """to_normalized ∘ to_original == id on gathered per-entity arrays."""
    e, d = 11, 6
    feat_idx = np.tile(np.arange(d, dtype=np.int32), (e, 1))
    feat_idx[:, -1] = -1  # padding column
    factors = np.abs(rng.normal(1.0, 0.3, 7)).astype(np.float32) + 0.2
    shifts = rng.normal(0, 1.0, 7).astype(np.float32)
    factors[0], shifts[0] = 1.0, 0.0  # intercept at global col 0
    norm = NormalizationContext(jnp.asarray(factors), jnp.asarray(shifts),
                                intercept_id=0)
    fac, shf, mask = gather_normalization(norm, jnp.asarray(feat_idx))
    assert np.allclose(np.asarray(fac)[:, -1], 1.0)
    assert np.allclose(np.asarray(shf)[:, -1], 0.0)
    assert np.array_equal(np.asarray(mask)[:, 0], np.ones(e))

    coef = rng.normal(0, 1, (e, d)).astype(np.float32)
    coef[:, -1] = 0.0  # padding coefficients are zero by construction
    normed = gathered_to_normalized_space(jnp.asarray(coef), fac, shf, mask)
    back = gathered_to_original_space(normed, fac, shf, mask)
    np.testing.assert_allclose(np.asarray(back), coef, atol=1e-5)


@pytest.mark.slow
def test_re_coordinate_normalized_kernel_matches_fallback(monkeypatch, rng):
    """End-to-end: a normalized + bounded RandomEffectCoordinate update
    routes through the kernel (interpret mode) and matches the NO_PALLAS
    fallback, with models in the original space both ways."""
    from photon_ml_tpu.algorithm.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.data.game_data import GameDataset
    from photon_ml_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )
    import scipy.sparse as sp

    n, d = 120, 7
    x = rng.normal(0, 1.0, (n, d))
    x *= np.array([1.0, 6.0, 0.3, 2.0, 1.0, 4.0, 0.5])[None, :]
    x[:, 0] = 1.0  # intercept
    ids = rng.integers(0, 9, n)
    y = (rng.random(n) < 0.5).astype(np.float64)
    data = GameDataset.build(
        responses=y,
        feature_shards={"shard": sp.csr_matrix(x)},
        ids={"userId": np.asarray([f"u{i}" for i in ids])})

    cfg_data = RandomEffectDataConfiguration(
        random_effect_type="userId", feature_shard_id="shard")
    ds = build_random_effect_dataset(data, cfg_data, intercept_col=0)

    std = np.maximum(x.std(axis=0), 1e-3)
    norm = NormalizationContext(
        jnp.asarray(1.0 / std, jnp.float32).at[0].set(1.0),
        jnp.asarray(x.mean(axis=0), jnp.float32).at[0].set(0.0),
        intercept_id=0)
    lb = np.full(d, -0.5, np.float32)
    ub = np.full(d, 0.5, np.float32)
    cfg = GLMOptimizationConfiguration(
        max_iterations=30, tolerance=1e-7, regularization_weight=1.0,
        regularization_context=RegularizationContext(RegularizationType.L2))

    def run(pallas: bool):
        if pallas:
            monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
            monkeypatch.delenv("PHOTON_ML_TPU_NO_PALLAS", raising=False)
        else:
            monkeypatch.setenv("PHOTON_ML_TPU_NO_PALLAS", "1")
            monkeypatch.delenv("PHOTON_ML_TPU_PALLAS_INTERPRET",
                               raising=False)
        coord = RandomEffectCoordinate(
            name="re", dataset=ds, task_type=TaskType.LOGISTIC_REGRESSION,
            config=cfg, normalization=norm,
            lower_bounds=jnp.asarray(lb), upper_bounds=jnp.asarray(ub))
        model = coord.initialize_model()
        new_model, _ = coord.update_model(model, None,
                                          jax.random.PRNGKey(0))
        return [np.asarray(c) for c in new_model.local_coefs]

    coefs_k = run(True)
    coefs_v = run(False)
    assert any(np.abs(c).max() > 1e-4 for c in coefs_k), "nothing learned"
    # The dataset blocks are f32 regardless of the suite's x64 config, and
    # kernel vs host are different Armijo solvers (projected + normalized)
    # agreeing to solver tolerance — f32-grade bound, not a golden one.
    for ck, cv in zip(coefs_k, coefs_v):
        np.testing.assert_allclose(ck, cv, atol=2e-3)


def test_re_coordinate_normalization_rejects_projected(rng):
    from photon_ml_tpu.algorithm.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.data.game_data import GameDataset
    from photon_ml_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )
    import scipy.sparse as sp

    n, d = 60, 12
    x = rng.normal(0, 1.0, (n, d))
    x[:, 0] = 1.0
    data = GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(np.float64),
        feature_shards={"shard": sp.csr_matrix(x)},
        ids={"userId": np.asarray([f"u{i % 5}" for i in range(n)])})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration(
            random_effect_type="userId", feature_shard_id="shard",
            projector_type="RANDOM=4"),
        intercept_col=0)
    cfg = GLMOptimizationConfiguration(
        max_iterations=5, tolerance=1e-7, regularization_weight=1.0,
        regularization_context=RegularizationContext(RegularizationType.L2))
    with pytest.raises(ValueError, match="projected"):
        RandomEffectCoordinate(
            name="re", dataset=ds, task_type=TaskType.LOGISTIC_REGRESSION,
            config=cfg,
            normalization=NormalizationContext(
                jnp.ones((d,)), None, intercept_id=0))


def test_norm_bounds_compose_with_entity_sharding(monkeypatch, rng):
    """The gathered normalization/bounds arrays ride through shard_map
    with the entity-sharded kernel (one kernel per device) and match the
    unsharded kernel solve."""
    from photon_ml_tpu.algorithm.coordinates import _solve_block
    from photon_ml_tpu.data.random_effect import EntityBlock
    from photon_ml_tpu.parallel import make_mesh, shard_block

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    e, r, d = 21, 5, 4
    scale = np.array([1.0, 7.0, 0.3, 2.0])
    x, y, off, w = _bucket(rng, e, r, d, dtype, scale=scale)
    block = EntityBlock(
        x=jnp.asarray(x), labels=jnp.asarray(y), offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
        row_ids=np.zeros((e, r), np.int32),
        feat_idx=np.broadcast_to(np.arange(d, dtype=np.int32), (e, d)))
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    factors, shifts = _standardization_arrays(rng, e, r, d, x, dtype)
    mask = jnp.zeros((e, d), dtype).at[:, 0].set(1.0)
    norm = (factors, shifts, mask)
    bounds = (jnp.full((e, d), -0.3, dtype), jnp.full((e, d), 0.3, dtype))

    def cfg(tol):
        return GLMOptimizationConfiguration(
            max_iterations=25, tolerance=tol, regularization_weight=0.4,
            regularization_context=RegularizationContext(
                RegularizationType.L2))

    monkeypatch.setenv("PHOTON_ML_TPU_PALLAS_INTERPRET", "1")
    plain = _solve_block(obj, cfg(1e-8), block, None,
                         jnp.zeros((e, d), dtype), norm=norm,
                         bounds=bounds)
    assert plain.value_history is None  # kernel path

    mesh = make_mesh()
    sblock = shard_block(block, mesh, sentinel_row=1000)
    ep = sblock.num_entities
    pad_e = ep - e

    def pad(a, fill):
        return jnp.concatenate(
            [a, jnp.full((pad_e, d), fill, a.dtype)])

    snorm = (pad(factors, 1.0), pad(shifts, 0.0), pad(mask, 0.0))
    sbounds = (pad(bounds[0], -0.3), pad(bounds[1], 0.3))
    sharded = _solve_block(obj, cfg(1.001e-8), sblock, None,
                           jnp.zeros((ep, d), dtype),
                           sharded=True, mesh=mesh, norm=snorm,
                           bounds=sbounds)
    assert sharded.value_history is None
    np.testing.assert_allclose(np.asarray(sharded.x[:e]),
                               np.asarray(plain.x),
                               atol=gold(1e-6, f32_floor=5e-3))
    np.testing.assert_array_equal(np.asarray(sharded.iterations[e:]), 0)


def test_bounds_clamp_solve_space_coefficients(rng):
    """Reference semantics: the optimizer ITERATE is the normalized-space
    coefficient vector (effectiveCoefficients = coef :* factors,
    ValueAndGradientAggregator.scala:100-120) and
    projectCoefficientsToHypercube clamps it against the RAW constraint
    values (LBFGS.scala:77) — so with factor normalization, the
    SOLVE-SPACE coefficients respect the box and the original-space
    model clamps at bound*factor."""
    from photon_ml_tpu.algorithm.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.data.game_data import GameDataset
    from photon_ml_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )
    import scipy.sparse as sp

    n, d = 200, 4
    x = rng.normal(0, 1.0, (n, d))
    x[:, 0] = 1.0
    # Strong signal on column 1 so its unconstrained coefficient is large.
    w_true = np.array([0.0, 3.0, 0.5, -0.5])
    z = x @ w_true
    y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float64)
    data = GameDataset.build(
        responses=y,
        feature_shards={"shard": sp.csr_matrix(x)},
        ids={"userId": np.asarray(["u0"] * n)})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration(
            random_effect_type="userId", feature_shard_id="shard"),
        intercept_col=0)
    # Factor-only normalization (no shifts): scale column 1 hard.
    factors = jnp.asarray([1.0, 0.1, 1.0, 1.0], jnp.float32)
    norm = NormalizationContext(factors, None, intercept_id=0)
    cap = 0.7
    lb = jnp.full((d,), -cap, jnp.float32)
    ub = jnp.full((d,), cap, jnp.float32)
    cfg = GLMOptimizationConfiguration(
        max_iterations=60, tolerance=1e-8, regularization_weight=0.01,
        regularization_context=RegularizationContext(RegularizationType.L2))
    coord = RandomEffectCoordinate(
        name="re", dataset=ds, task_type=TaskType.LOGISTIC_REGRESSION,
        config=cfg, normalization=norm,
        lower_bounds=lb, upper_bounds=ub)
    model, _ = coord.update_model(coord.initialize_model(), None,
                                  jax.random.PRNGKey(0))
    coefs = np.concatenate([np.asarray(c)
                            for c in model.local_coefs], axis=0)
    coefs = coefs[:, :d]  # strip padding columns (local cols 0..d-1
    # map to global cols 0..d-1: single entity set, all observed)
    # Solve-space coefficients (w' = w / factor; no shifts here) respect
    # the box...
    solve_space = coefs / np.asarray(factors)[None, :]
    assert (np.abs(solve_space) <= cap + 1e-4).all(), solve_space
    # ...the box is actually ACTIVE (the unconstrained solve-space
    # coefficient on the strong column exceeds the cap)...
    assert np.isclose(np.abs(solve_space).max(), cap, atol=1e-3)
    # ...and the ORIGINAL-space coefficient on the hard-scaled column 1
    # (factor 0.1) therefore clamps at cap*factor, NOT at the raw cap.
    assert np.abs(coefs[:, 1]).max() <= cap * 0.1 + 1e-4, coefs


def test_mesh_sharded_coordinate_with_shift_normalization(rng):
    """Sentinel padding entities added by entity sharding (feat_idx == -1
    everywhere) must not trip the intercept-present validation — mesh +
    STANDARDIZATION is a supported composition."""
    from photon_ml_tpu.algorithm.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.data.game_data import GameDataset
    from photon_ml_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.parallel import make_mesh
    import scipy.sparse as sp

    n, d = 90, 5
    x = rng.normal(0, 1.0, (n, d))
    x[:, 0] = 1.0
    # 9 users — NOT divisible by the 8-device mesh: sharding pads with
    # sentinel entities.
    data = GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(np.float64),
        feature_shards={"shard": sp.csr_matrix(x)},
        ids={"userId": np.asarray([f"u{i % 9}" for i in range(n)])})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration(
            random_effect_type="userId", feature_shard_id="shard"),
        intercept_col=0)
    norm = NormalizationContext(
        jnp.ones((d,), jnp.float32),
        jnp.asarray(x.mean(axis=0), jnp.float32).at[0].set(0.0),
        intercept_id=0)
    cfg = GLMOptimizationConfiguration(
        max_iterations=10, tolerance=1e-6, regularization_weight=1.0,
        regularization_context=RegularizationContext(RegularizationType.L2))
    coord = RandomEffectCoordinate(
        name="re", dataset=ds, task_type=TaskType.LOGISTIC_REGRESSION,
        config=cfg, normalization=norm, mesh=make_mesh())
    model, _ = coord.update_model(coord.initialize_model(), None,
                                  jax.random.PRNGKey(0))
    assert any(np.abs(np.asarray(c)).max() > 1e-5
               for c in model.local_coefs)
