"""Factored random-effect (matrix-factorization) coordinate tests.

Mirrors the reference's FactoredRandomEffectCoordinate integration tests:
alternating per-entity latent solves with the shared projection-matrix refit
(ml/algorithm/FactoredRandomEffectCoordinate.scala:99-165).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from photon_ml_tpu.algorithm import FactoredRandomEffectCoordinate
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.evaluation.evaluators import SquaredLossEvaluator
from photon_ml_tpu.models import FactoredRandomEffectModel
from photon_ml_tpu.ops.features import KroneckerFeatures
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    MFOptimizationConfiguration,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.types import TaskType


def test_mf_config_parse_roundtrip():
    cfg = MFOptimizationConfiguration.parse("3,8")
    assert cfg.max_iterations == 3 and cfg.num_factors == 8
    assert MFOptimizationConfiguration.parse(cfg.to_string()) == cfg
    assert MFOptimizationConfiguration.from_json(cfg.to_json()) == cfg
    with pytest.raises(ValueError):
        MFOptimizationConfiguration.parse("3")
    with pytest.raises(ValueError):
        MFOptimizationConfiguration(max_iterations=0, num_factors=2)


def test_kronecker_features_match_materialized(rng):
    n, d, k = 12, 5, 3
    x = jnp.asarray(rng.normal(0, 1, (n, d)))
    g = jnp.asarray(rng.normal(0, 1, (n, k)))
    feats = KroneckerFeatures(x, g)
    assert feats.num_features == k * d
    # Materialized virtual matrix: row i = vec(γ_i ⊗ x_i), index (a,j)->a*d+j.
    m = np.einsum("nk,nd->nkd", np.asarray(g), np.asarray(x)).reshape(n, k * d)
    v = jnp.asarray(rng.normal(0, 1, (k * d,)))
    u = jnp.asarray(rng.normal(0, 1, (n,)))
    np.testing.assert_allclose(feats.matvec(v), m @ np.asarray(v), rtol=1e-6)
    np.testing.assert_allclose(feats.rmatvec(u), np.asarray(u) @ m, rtol=1e-6)
    np.testing.assert_allclose(
        feats.row_sq_matvec(v), (m * m) @ np.asarray(v), rtol=1e-6)
    np.testing.assert_allclose(
        feats.sq_rmatvec(u), np.asarray(u) @ (m * m), rtol=1e-6)


def _low_rank_fixture(rng, n=600, d=12, n_users=15, k_true=2):
    """Linear responses from a rank-k_true per-entity coefficient structure."""
    x = rng.normal(0, 1, (n, d))
    users = rng.integers(0, n_users, n)
    b_true = rng.normal(0, 1.0, (k_true, d))
    g_true = rng.normal(0, 1.0, (n_users, k_true))
    coefs = g_true @ b_true  # [n_users, d]
    y = np.einsum("nd,nd->n", x, coefs[users]) + rng.normal(0, 0.05, n)
    data = GameDataset.build(
        responses=y,
        feature_shards={"s": sp.csr_matrix(x)},
        ids={"userId": np.asarray([f"u{u}" for u in users])})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "s",
                                            projector_type="IDENTITY"))
    return data, ds, y


def test_factored_coordinate_learns_low_rank_structure(rng):
    data, ds, y = _low_rank_fixture(rng)
    l2 = RegularizationContext(RegularizationType.L2)
    coord = FactoredRandomEffectCoordinate(
        name="perUserMF", dataset=ds,
        task_type=TaskType.LINEAR_REGRESSION,
        config=GLMOptimizationConfiguration(
            max_iterations=30, tolerance=1e-8, regularization_weight=1e-3,
            regularization_context=l2),
        latent_config=GLMOptimizationConfiguration(
            max_iterations=30, tolerance=1e-8, regularization_weight=1e-3,
            regularization_context=l2),
        mf_config=MFOptimizationConfiguration(max_iterations=3, num_factors=2))
    model = coord.initialize_model()
    assert isinstance(model, FactoredRandomEffectModel)
    assert model.projection_matrix.shape == (2, ds.num_global_features)

    ev = SquaredLossEvaluator()
    s0 = np.asarray(coord.score(model))
    loss0 = ev.evaluate(s0, y)
    model, trackers = coord.update_model(model, None, jax.random.key(0))
    s1 = np.asarray(coord.score(model))
    loss1 = ev.evaluate(s1, y)
    assert len(trackers) == 3
    # The alternation must explain most of the variance (rank-2 truth).
    assert loss1 < 0.2 * loss0, (loss0, loss1)

    # score == x . (γᵀB) per row, via the global-space model matrix.
    g = model.score_numpy(data)
    np.testing.assert_allclose(s1, g, rtol=1e-3, atol=1e-4)


def test_factored_model_persists_latent_artifacts(rng, tmp_path):
    """Saving a GAME model with a factored coordinate writes BOTH the
    converted original-space coefficients (the reference's on-disk form)
    AND the latent decomposition (per-entity gamma + projection B as
    LatentFactorAvro, the schema of ModelProcessingUtils.scala:400-424),
    with the MF config recorded in model-metadata.json."""
    import json

    from photon_ml_tpu.io.avro_codec import read_container
    from photon_ml_tpu.io.model_io import load_game_model, save_game_model
    from photon_ml_tpu.models.game_model import GameModel
    from photon_ml_tpu.data.index_map import IdentityIndexMap

    data, ds, y = _low_rank_fixture(rng)
    l2 = RegularizationContext(RegularizationType.L2)
    cfg = GLMOptimizationConfiguration(
        max_iterations=20, tolerance=1e-8, regularization_weight=1e-3,
        regularization_context=l2)
    coord = FactoredRandomEffectCoordinate(
        name="perUserMF", dataset=ds, task_type=TaskType.LINEAR_REGRESSION,
        config=cfg, latent_config=cfg,
        mf_config=MFOptimizationConfiguration(max_iterations=2,
                                              num_factors=2))
    model, _ = coord.update_model(coord.initialize_model(), None,
                                  jax.random.key(0))
    gm = GameModel({"perUserMF": model}, TaskType.LINEAR_REGRESSION)
    imap = IdentityIndexMap(ds.num_global_features)
    save_game_model(tmp_path, gm, {model.feature_shard_id: imap})

    latent_dir = tmp_path / "random-effect" / "perUserMF" / "latent"
    gammas = list(read_container(latent_dir / "gamma-latent-factors.avro"))
    proj = list(read_container(
        latent_dir / "projection-latent-factors.avro"))
    assert len(gammas) == model.num_entities
    assert all(len(r["latentFactor"]) == 2 for r in gammas)
    assert len(proj) == 2
    assert all(len(r["latentFactor"]) == ds.num_global_features
               for r in proj)
    # gamma^T B reconstructs each entity's saved original-space row.
    by_id = {r["effectId"]: np.asarray(r["latentFactor"]) for r in gammas}
    b = np.asarray([r["latentFactor"] for r in proj])
    entity_rows = model.to_entity_dict()
    for name, (cols, vals) in list(entity_rows.items())[:5]:
        dense = np.zeros(ds.num_global_features)
        dense[cols] = vals
        np.testing.assert_allclose(by_id[name] @ b, dense, atol=1e-5)

    meta = json.loads((tmp_path / "model-metadata.json").read_text())
    (coord_meta,) = meta["coordinates"]
    assert coord_meta["factored"] == {"numFactors": 2, "mfMaxIterations": 2}
    # Loads back as a plain random-effect model (reference behavior).
    loaded = load_game_model(tmp_path,
                             {model.feature_shard_id: imap})
    assert "perUserMF" in loaded.models


def test_factored_coordinate_requires_identity_blocks(rng):
    data, _, _ = _low_rank_fixture(rng, n=60, d=6, n_users=4)
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "s",
                                            projector_type="RANDOM=2"))
    cfg = GLMOptimizationConfiguration(max_iterations=2, tolerance=1e-4)
    with pytest.raises(ValueError, match="IDENTITY"):
        FactoredRandomEffectCoordinate(
            name="bad", dataset=ds, task_type=TaskType.LINEAR_REGRESSION,
            config=cfg, latent_config=cfg,
            mf_config=MFOptimizationConfiguration(1, 2))


def test_factored_residual_offsets_shift_solution(rng):
    data, ds, y = _low_rank_fixture(rng, n=200, d=8, n_users=6)
    cfg = GLMOptimizationConfiguration(max_iterations=15, tolerance=1e-7)
    coord = FactoredRandomEffectCoordinate(
        name="mf", dataset=ds, task_type=TaskType.LINEAR_REGRESSION,
        config=cfg, latent_config=cfg,
        mf_config=MFOptimizationConfiguration(2, 2))
    model = coord.initialize_model()
    m_plain, _ = coord.update_model(model, None, jax.random.key(0))
    # A residual equal to y leaves ~nothing for the coordinate to explain.
    residual = jnp.asarray(y, jnp.float32) if jnp is not None else y
    m_resid, _ = coord.update_model(model, residual, jax.random.key(0))
    s_plain = np.asarray(coord.score(m_plain))
    s_resid = np.asarray(coord.score(m_resid))
    assert np.abs(s_resid).mean() < 0.25 * np.abs(s_plain).mean()
