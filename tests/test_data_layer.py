"""Data layer tests: GameDataset, random-effect bucketing, sampling,
LibSVM ingest, stats, validators.

Mirrors the reference's data-tier tests (LocalDataSetTest,
RandomEffectDataSetTest + integration builders in GameTestUtils).
"""

import numpy as np
import jax.numpy as jnp
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.libsvm import read_libsvm
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
    pearson_correlation_scores,
)
from photon_ml_tpu.data.sampling import (
    binary_classification_down_sampler,
    reservoir_sample,
)
from photon_ml_tpu.data.stats import BasicStatisticalSummary
from photon_ml_tpu.data.validators import validate_data
from photon_ml_tpu.types import DataValidationType, TaskType

import jax


def _toy_game_data(rng, n=60, d=10, n_users=7):
    x = sp.random(n, d, density=0.4, random_state=3, format="csr")
    x[:, d - 1] = 1.0  # intercept
    users = rng.integers(0, n_users, n)
    y = (rng.random(n) < 0.5).astype(float)
    return GameDataset.build(
        responses=y,
        feature_shards={"shard": sp.csr_matrix(x)},
        ids={"userId": np.asarray([f"u{u}" for u in users])},
        offsets=rng.normal(0, 0.1, n),
        weights=rng.random(n) + 0.5,
    )


def test_game_dataset_build_and_codes(rng):
    data = _toy_game_data(rng)
    col = data.id_columns["userId"]
    assert col.num_entities <= 7
    # codes round-trip through the vocabulary
    names = col.vocabulary[col.codes]
    assert names[0].startswith("u")
    batch = data.fixed_effect_batch("shard")
    assert batch.num_rows == data.num_rows


def test_random_effect_blocks_cover_all_rows(rng):
    data = _toy_game_data(rng)
    cfg = RandomEffectDataConfiguration("userId", "shard")
    ds = build_random_effect_dataset(data, cfg, intercept_col=9)
    # Every row appears exactly once across active blocks (no cap set).
    seen = np.concatenate([
        np.asarray(b.row_ids).ravel() for b in ds.blocks])
    seen = seen[seen < ds.n_rows]
    assert sorted(seen) == list(range(data.num_rows))
    assert ds.num_entities == data.id_columns["userId"].num_entities
    # Block features match the original matrix through the gather map.
    b = ds.blocks[0]
    mat = data.feature_shards["shard"].toarray()
    for e in range(b.num_entities):
        fidx = np.asarray(b.feat_idx[e])
        valid_cols = fidx >= 0
        for r in range(b.n_pad):
            gr = int(b.row_ids[e, r])
            if gr == ds.n_rows:
                assert float(b.weights[e, r]) == 0.0
                continue
            np.testing.assert_allclose(
                np.asarray(b.x[e, r])[valid_cols], mat[gr, fidx[valid_cols]])


def test_random_effect_active_cap_and_passive(rng):
    data = _toy_game_data(rng, n=200, n_users=4)
    cfg = RandomEffectDataConfiguration(
        "userId", "shard", num_active_data_points=16)
    ds = build_random_effect_dataset(data, cfg, seed=1, intercept_col=9)
    active_rows = sum(
        int((np.asarray(b.row_ids) < ds.n_rows).sum()) for b in ds.blocks)
    passive_rows = sum(
        int((np.asarray(b.row_ids) < ds.n_rows).sum())
        for b in ds.passive_blocks if b is not None)
    assert active_rows == 16 * 4
    assert active_rows + passive_rows == 200
    # Reweighting preserves total weight per entity approximately:
    # sum of active weights == sum of original weights for that entity.
    col = data.id_columns["userId"]
    for b, codes in zip(ds.blocks, ds.entity_codes):
        for e, code in enumerate(codes):
            total_orig = data.weights[col.codes == code].sum()
            active_w = float(np.asarray(b.weights[e]).sum())
            np.testing.assert_allclose(active_w, total_orig, rtol=0.35)


def test_feature_selection_ratio_caps_dims(rng):
    data = _toy_game_data(rng, n=120, d=30, n_users=3)
    cfg = RandomEffectDataConfiguration(
        "userId", "shard", num_features_to_samples_ratio=0.2)
    ds = build_random_effect_dataset(data, cfg, intercept_col=29)
    for b, codes in zip(ds.blocks, ds.entity_codes):
        n_active = (np.asarray(b.row_ids) < ds.n_rows).sum(axis=1)
        d_local = (np.asarray(b.feat_idx) >= 0).sum(axis=1)
        for e in range(b.num_entities):
            keep = max(1, int(np.ceil(0.2 * n_active[e])))
            assert d_local[e] <= keep + 1  # +1 in case intercept forced in
            # intercept always survives
            assert 29 in np.asarray(b.feat_idx[e])


def test_pearson_scores_match_numpy(rng):
    x = rng.normal(0, 1, (50, 4))
    x[:, 2] = 1.0  # constant/intercept
    y = rng.normal(0, 1, 50)
    scores = pearson_correlation_scores(sp.csr_matrix(x), y, intercept_col=2)
    for j in (0, 1, 3):
        expect = abs(np.corrcoef(x[:, j], y)[0, 1])
        np.testing.assert_allclose(scores[j], expect, rtol=1e-10)
    assert np.isinf(scores[2])


def test_scatter_scores_roundtrip(rng):
    data = _toy_game_data(rng)
    cfg = RandomEffectDataConfiguration("userId", "shard")
    ds = build_random_effect_dataset(data, cfg, intercept_col=9)
    # margins == 1 for every real row -> score vector of ones
    margins = [jnp.where(b.row_ids < ds.n_rows, 1.0, 123.0) for b in ds.blocks]
    scores = ds.scatter_scores(margins, [None] * len(ds.blocks))
    np.testing.assert_allclose(np.asarray(scores), np.ones(data.num_rows))


def test_reservoir_sample_properties(rng):
    idx, mult = reservoir_sample(rng, 100, 10)
    assert len(idx) == 10 and mult == 10.0
    assert len(np.unique(idx)) == 10
    idx, mult = reservoir_sample(rng, 5, 10)
    assert len(idx) == 5 and mult == 1.0


def test_binary_down_sampler_keeps_positives():
    key = jax.random.PRNGKey(0)
    labels = jnp.asarray([1.0, 1.0, 0.0, 0.0] * 50)
    weights = jnp.ones(200)
    w = binary_classification_down_sampler(key, labels, weights, 0.3)
    w = np.asarray(w)
    assert np.all(w[::4] == 1.0) and np.all(w[1::4] == 1.0)
    negs = np.concatenate([w[2::4], w[3::4]])
    nz = negs[negs != 0]
    np.testing.assert_allclose(nz, 1 / 0.3, rtol=1e-6)
    # Unbiasedness in expectation: kept negative weight ~ total negatives.
    assert abs(negs.sum() - 100) < 40


def test_libsvm_reader(tmp_path):
    p = tmp_path / "data.libsvm"
    p.write_text("+1 1:0.5 3:2.0\n-1 2:1.5 # comment\n0 1:1.0 4:1.0\n")
    mat, y = read_libsvm(p, add_intercept=True)
    assert mat.shape == (3, 5)  # 4 features + intercept
    np.testing.assert_allclose(y, [1.0, 0.0, 0.0])
    np.testing.assert_allclose(mat.toarray()[:, -1], 1.0)
    assert mat[0, 0] == 0.5 and mat[0, 2] == 2.0 and mat[1, 1] == 1.5

    bad = tmp_path / "bad.libsvm"
    bad.write_text("1 nonsense\n")
    with pytest.raises(ValueError, match="bad.libsvm:1"):
        read_libsvm(bad)


def test_stats_sparse_includes_implicit_zeros(rng):
    x = sp.csr_matrix(np.asarray([[1.0, 0.0], [3.0, -2.0], [0.0, 0.0]]))
    s = BasicStatisticalSummary.compute(x)
    np.testing.assert_allclose(s.mean, [4 / 3, -2 / 3])
    np.testing.assert_allclose(s.max, [3.0, 0.0])
    np.testing.assert_allclose(s.min, [0.0, -2.0])
    np.testing.assert_allclose(s.num_nonzeros, [2, 1])
    dense = BasicStatisticalSummary.compute(x.toarray())
    np.testing.assert_allclose(dense.variance, s.variance)
    np.testing.assert_allclose(dense.mean_abs, s.mean_abs)


def test_validators():
    x = sp.csr_matrix(np.ones((4, 2)))
    validate_data(TaskType.LOGISTIC_REGRESSION, x,
                  np.asarray([0.0, 1.0, 0, 1]))
    with pytest.raises(ValueError, match="binary"):
        validate_data(TaskType.LOGISTIC_REGRESSION, x,
                      np.asarray([0.0, 2.0, 0, 1]))
    with pytest.raises(ValueError, match="non-negative"):
        validate_data(TaskType.POISSON_REGRESSION, x,
                      np.asarray([1.0, -1.0, 0, 1]))
    with pytest.raises(ValueError, match="non-finite"):
        validate_data(TaskType.LINEAR_REGRESSION, x,
                      np.asarray([1.0, np.nan, 0, 1]))
    with pytest.raises(ValueError, match="weights"):
        validate_data(TaskType.LINEAR_REGRESSION, x,
                      np.asarray([1.0, 1.0, 0, 1]),
                      weights=np.asarray([1.0, -2.0, 1, 1]))
    # disabled mode never raises
    validate_data(TaskType.LOGISTIC_REGRESSION, x, np.asarray([5.0] * 4),
                  validation_type=DataValidationType.VALIDATE_DISABLED)


def test_re_config_parse():
    c = RandomEffectDataConfiguration.parse(
        "userId,shard1,10,100,20,0.5,INDEX_MAP")
    assert c.random_effect_type == "userId"
    assert c.num_active_data_points == 100
    assert c.num_passive_data_points_lower_bound == 20
    assert c.num_features_to_samples_ratio == 0.5
    c2 = RandomEffectDataConfiguration.parse("itemId,shard2,4,-1,-1,-1")
    assert c2.num_active_data_points is None
    with pytest.raises(ValueError):
        RandomEffectDataConfiguration.parse("tooFew,fields")


def test_filter_features_by_support():
    import scipy.sparse as sp

    from photon_ml_tpu.data.random_effect import filter_features_by_support

    x = sp.csr_matrix(np.array([
        [1.0, 0.0, 2.0, 1.0],
        [0.0, 0.0, 3.0, 1.0],
        [4.0, 0.0, 0.0, 1.0],
    ]))
    # support per column: [2, 0, 2, 3]
    np.testing.assert_array_equal(
        filter_features_by_support(x, 2), [0, 2, 3])
    np.testing.assert_array_equal(
        filter_features_by_support(x, 3), [3])
    # intercept column always survives
    np.testing.assert_array_equal(
        filter_features_by_support(x, 5, intercept_col=3), [3])
