"""Async serving front-end (photon_ml_tpu/serving/frontend.py):
cross-request coalescing parity, admission-control contract, multi-model
tenancy over one shared executable cache, and atomic hot-swap. The
ENGINE semantics (bucketing, padding isolation, kernels) are covered by
test_serving.py; under test here is the front door: the event-loop
request path and the model registry."""

import asyncio

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    LogisticRegressionModel,
    MatrixFactorizationModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    BucketLadder,
    FrontendConfig,
    FrontendError,
    RequestRejected,
    ServingFrontend,
    StreamingGameScorer,
    UnknownModelError,
)
from photon_ml_tpu.types import TaskType

DT = jnp.float64

LADDER = dict(min_rows=8, max_rows=64)


def _dataset(rng, n=60, d=6, n_users=7, n_items=5):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    users = rng.integers(0, n_users, n).astype(str)
    items = rng.integers(0, n_items, n).astype(str)
    user_x = sp.csr_matrix(np.hstack(
        [rng.normal(0, 1, (n, 2)), np.ones((n, 1))]))
    return GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"global": sp.csr_matrix(x), "user": user_x},
        ids={"userId": users, "itemId": items})


def _game_model(rng, train):
    ds = build_random_effect_dataset(
        train, RandomEffectDataConfiguration("userId", "user"),
        intercept_col=2)
    re = RandomEffectModel.zeros_like_dataset(ds, dtype=DT)
    re = re.with_coefs([jnp.asarray(rng.normal(0, 1, np.asarray(c).shape))
                        for c in re.local_coefs])
    fe = FixedEffectModel(
        LogisticRegressionModel(Coefficients(
            jnp.asarray(rng.normal(0, 1, 6)))), "global")
    mf = MatrixFactorizationModel(
        "userId", "itemId",
        jnp.asarray(rng.normal(0, 1, (7, 3))),
        jnp.asarray(rng.normal(0, 1, (5, 3))),
        np.unique(train.id_columns["userId"].vocabulary),
        np.unique(train.id_columns["itemId"].vocabulary))
    return GameModel({"fixed": fe, "perUser": re, "mf": mf},
                     TaskType.LOGISTIC_REGRESSION)


def _variant(model: GameModel, factor: float) -> GameModel:
    """Same-STRUCTURE weight variant (the A/B tenancy shape): every
    coordinate keeps its shapes/vocabs, fixed-effect weights scale."""
    fe = model.models["fixed"]
    glm = type(fe.glm)(Coefficients(
        jnp.asarray(fe.glm.coefficients.means) * factor))
    return model.update_model("fixed", FixedEffectModel(
        glm, fe.feature_shard_id))


@pytest.fixture
def frontend_and_model(rng):
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    fe = ServingFrontend({"default": gm}, dtype=DT,
                         ladder=BucketLadder(**LADDER),
                         config=FrontendConfig(coalesce_window_s=0.001,
                                               max_pending=256))
    return fe, gm


def _singles(seed0, k, n=1):
    return [_dataset(np.random.default_rng(seed0 + i), n=n)
            for i in range(k)]


# -- coalescing parity -----------------------------------------------------

@pytest.mark.needs_f64
def test_concurrent_singles_coalesce_and_match_host(frontend_and_model):
    fe, gm = frontend_and_model
    reqs = _singles(100, 40)
    results, info = fe.replay(reqs, concurrency=8)
    assert info["shed"] == 0 and info["errors"] == 0
    for r, o in zip(reqs, results):
        np.testing.assert_allclose(o, gm.score(r), rtol=1e-10, atol=1e-10)
    st = fe.stats()
    # Coalescing genuinely happened: far fewer device dispatches than
    # requests (8 concurrent requesters, 1 ms window).
    assert st["engines"]["default"]["dispatches"] < len(reqs)
    assert st["engines"]["default"]["requests"] == len(reqs)
    assert st["completed"] == len(reqs) and st["admitted"] == len(reqs)


@pytest.mark.needs_f64
def test_full_window_coalesces_to_one_dispatch(frontend_and_model):
    """All requests inside one (generous) window and under max_rows must
    share ONE bucket dispatch."""
    fe, gm = frontend_and_model
    fe.coalesce_window_s = 0.25
    reqs = _singles(200, 16)
    results, _ = fe.replay(reqs, arrivals=[0.0] * len(reqs))
    for r, o in zip(reqs, results):
        np.testing.assert_allclose(o, gm.score(r), rtol=1e-10, atol=1e-10)
    st = fe.stats()
    assert st["engines"]["default"]["dispatches"] == 1
    assert st["coalesced_groups"] == 1


@pytest.mark.needs_f64
def test_zero_row_and_oversized_requests(frontend_and_model):
    """BucketLadder edges through the front door: a zero-row request
    settles empty without a dispatch; a request beyond the top bucket
    splits inside the engine and still matches host scoring."""
    fe, gm = frontend_and_model
    big = _dataset(np.random.default_rng(7), n=150)  # > max_rows=64
    zero = _dataset(np.random.default_rng(8), n=20).subset(np.arange(0))
    results, info = fe.replay([big, zero], concurrency=2)
    assert info["shed"] == 0 and info["errors"] == 0
    np.testing.assert_allclose(results[0], gm.score(big),
                               rtol=1e-10, atol=1e-10)
    assert results[1].shape == (0,)


@pytest.mark.needs_f64
def test_bad_request_is_isolated_from_its_window(frontend_and_model):
    """A malformed request must error ALONE: the requests it was
    coalesced with still score (the group retries per-request)."""
    fe, gm = frontend_and_model
    fe.coalesce_window_s = 0.25
    good = _singles(300, 6)
    bad = GameDataset.build(
        responses=np.zeros(1),
        feature_shards={"global": sp.csr_matrix(np.ones((1, 6)))},
        ids={})  # missing 'user' shard and id columns

    async def run():
        async with fe:
            tasks = [asyncio.ensure_future(fe.score(r))
                     for r in good[:3] + [bad] + good[3:]]
            return await asyncio.gather(*tasks, return_exceptions=True)

    out = asyncio.run(run())
    assert isinstance(out[3], KeyError)
    for r, o in zip(good, out[:3] + out[4:]):
        np.testing.assert_allclose(o, gm.score(r), rtol=1e-10, atol=1e-10)
    assert fe.stats()["isolation_splits"] == 1
    assert fe.stats()["failed"] == 1


# -- admission control -----------------------------------------------------

@pytest.mark.needs_f64
def test_queue_full_rejection_contract(rng):
    """Past max_pending, score() raises a TYPED rejection immediately
    (fields: model/pending/limit); admitted requests still complete."""
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    fe = ServingFrontend({"default": gm}, dtype=DT,
                         ladder=BucketLadder(**LADDER),
                         config=FrontendConfig(coalesce_window_s=0.1,
                                               max_pending=4))
    reqs = _singles(400, 32)
    results, info = fe.replay(reqs, arrivals=[0.0] * len(reqs))
    # All 32 submit inside the window: exactly max_pending admitted.
    assert info["completed"] == 4 and info["shed"] == 28
    st = fe.stats()
    assert st["rejected"] == 28 and st["admitted"] == 4
    done = [r for r in results if r is not None]
    assert len(done) == 4

    async def one_reject():
        async with fe:
            tasks = [asyncio.ensure_future(fe.score(r))
                     for r in reqs[:4]]
            await asyncio.sleep(0)  # admit the four
            with pytest.raises(RequestRejected) as ei:
                await fe.score(reqs[4])
            assert ei.value.model == "default"
            assert ei.value.pending == 4 and ei.value.limit == 4
            await asyncio.gather(*tasks)

    asyncio.run(one_reject())


@pytest.mark.needs_f64
def test_unknown_model_and_not_started(frontend_and_model):
    fe, _ = frontend_and_model
    req = _singles(500, 1)[0]

    async def unknown():
        async with fe:
            with pytest.raises(UnknownModelError):
                await fe.score(req, model="nope")

    asyncio.run(unknown())
    with pytest.raises(FrontendError, match="not started"):
        asyncio.run(fe.score(req))


def test_score_during_close_is_refused_not_hung(frontend_and_model):
    """close() drains what was admitted before it; a request admitted
    after the batcher's final drain would never be grouped — score()
    must refuse with a typed error instead of hanging its caller."""
    fe, _ = frontend_and_model
    req = _singles(500, 1)[0]

    async def run():
        await fe.start()
        first = await fe.score(req)  # normal request settles
        closer = asyncio.ensure_future(fe.close())
        await asyncio.sleep(0)  # close() sets _closing, starts draining
        with pytest.raises(FrontendError, match="closing"):
            await fe.score(req)
        await closer
        return first

    assert asyncio.run(run()).shape == (1,)


@pytest.mark.needs_f64
def test_isolation_retry_does_not_overcount(rng):
    """Regression (PR 8 docstring caveat, now fixed): a coalesce window
    whose score_many spans SEVERAL internal dispatch groups fails on a
    late group -> the solo retry used to re-count requests the failed
    attempt had already counted. With the checkpoint/rollback the engine
    counters equal the requests actually SERVED, and the registry obeys
    admitted == completed + failed exactly."""
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    fe = ServingFrontend({"default": gm}, dtype=DT,
                         ladder=BucketLadder(**LADDER),
                         config=FrontendConfig(coalesce_window_s=0.25,
                                               max_pending=256))
    # 3x30-row requests + 1 malformed: inside ONE coalesce window the
    # engine packs [30, 30] (60 <= max_rows=64) as dispatch group 1 and
    # [30, bad] as group 2 — group 1 is counted AND dispatched before
    # the bad request's featureization raises.
    goods = [_dataset(np.random.default_rng(800 + i), n=30)
             for i in range(3)]
    bad = GameDataset.build(
        responses=np.zeros(1),
        feature_shards={"global": sp.csr_matrix(np.ones((1, 6)))},
        ids={})  # missing 'user' shard and id columns
    telemetry.reset()
    telemetry.enable()
    try:
        results, info = fe.replay(goods + [bad],
                                  arrivals=[0.0] * 4)
        assert info["errors"] == 1 and info["shed"] == 0
        for r, o in zip(goods, results[:3]):
            np.testing.assert_allclose(o, gm.score(r),
                                       rtol=1e-10, atol=1e-10)
        st = fe.stats()
        assert st["isolation_splits"] == 1
        # Engine accounting == requests actually served (3), not the
        # 5 the double-count produced (2 in the failed attempt's
        # completed group + 3 solo retries).
        eng = st["engines"]["default"]
        assert eng["requests"] == 3
        assert eng["rows_scored"] == 90
        snap = telemetry.snapshot()
        assert snap["counters"]["serving.requests"] == 3
        assert snap["counters"]["serving.model.default.requests"] == 3
        assert snap["counters"]["serving.rows_scored"] == 90
        # Conservation law on the front-end registry family.
        c = snap["counters"]
        assert c["serving.frontend.admitted"] == 4
        assert c["serving.frontend.completed"] == 3
        assert c["serving.frontend.failed"] == 1
        assert (c["serving.frontend.completed"]
                + c["serving.frontend.failed"]
                == c["serving.frontend.admitted"])
        assert st["admitted"] == st["completed"] + st["failed"] == 4
    finally:
        telemetry.disable()
        telemetry.reset()


@pytest.mark.needs_f64
def test_per_model_quota_protects_quiet_tenant(rng):
    """Satellite: max_pending_per_model sheds the hot tenant at ITS
    quota (typed rejection, scope='model', per-model rejected counters)
    while a quiet tenant keeps admitting into the shared process
    bound."""
    train = _dataset(rng, n=80)
    gm_a = _game_model(rng, train)
    gm_b = _variant(gm_a, 2.0)
    fe = ServingFrontend(
        {"hot": gm_a, "quiet": gm_b}, dtype=DT,
        ladder=BucketLadder(**LADDER),
        config=FrontendConfig(coalesce_window_s=0.2, max_pending=64,
                              max_pending_per_model=2))
    reqs = _singles(900, 8)
    telemetry.reset()
    telemetry.enable()
    try:

        async def run():
            async with fe:
                hot = [asyncio.ensure_future(fe.score(r, model="hot"))
                       for r in reqs[:2]]
                await asyncio.sleep(0)  # admit the hot pair
                assert fe.stats()["pending_by_model"]["hot"] == 2
                # Hot tenant at quota: typed per-model shed, process
                # still has 62 slots of headroom.
                with pytest.raises(RequestRejected) as ei:
                    await fe.score(reqs[2], model="hot")
                assert ei.value.scope == "model"
                assert ei.value.model == "hot"
                assert ei.value.pending == 2 and ei.value.limit == 2
                # The quiet tenant is unaffected by the hot one's quota.
                quiet = await fe.score(reqs[3], model="quiet")
                return await asyncio.gather(*hot), quiet

        hot_out, quiet_out = asyncio.run(run())
        assert len(hot_out) == 2 and quiet_out is not None
        st = fe.stats()
        assert st["rejected"] == 1
        assert st["rejected_by_model"] == {"hot": 1}
        assert st["completed"] == 3 and st["admitted"] == 3
        snap = telemetry.snapshot()
        assert snap["counters"]["serving.model.hot.rejected"] == 1
        assert "serving.model.quiet.rejected" not in snap["counters"]
    finally:
        telemetry.disable()
        telemetry.reset()


# -- multi-model tenancy ---------------------------------------------------

@pytest.mark.needs_f64
def test_tenancy_routes_models_and_shares_executables(rng, tracing_guard):
    """Two same-structure models resident: requests route to the right
    weights, and the SHARED cache compiles one executable population —
    bounded by the single-model ladder expectation, never
    models x buckets."""
    train = _dataset(rng, n=80)
    gm_a = _game_model(rng, train)
    gm_b = _variant(gm_a, 3.0)
    fe = ServingFrontend({"a": gm_a, "b": gm_b}, dtype=DT,
                         ladder=BucketLadder(**LADDER),
                         tracing_guard=tracing_guard,
                         config=FrontendConfig(coalesce_window_s=0.002))
    sizes = [1, 3, 9, 17, 33, 2, 5]
    reqs = [_dataset(np.random.default_rng(600 + i), n=k)
            for i, k in enumerate(sizes)]

    async def run():
        async with fe:
            ta = [asyncio.ensure_future(fe.score(r, model="a"))
                  for r in reqs]
            tb = [asyncio.ensure_future(fe.score(r, model="b"))
                  for r in reqs]
            return (await asyncio.gather(*ta), await asyncio.gather(*tb))

    outs_a, outs_b = asyncio.run(run())
    for r, oa, ob in zip(reqs, outs_a, outs_b):
        np.testing.assert_allclose(oa, gm_a.score(r),
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(ob, gm_b.score(r),
                                   rtol=1e-10, atol=1e-10)
        # the variant genuinely scores differently (no misrouting both
        # ways onto one model)
        assert not np.allclose(oa, ob)
    # Shared-cache compile math: both engines' buckets land in ONE
    # population; same structure (param shapes included in the key) ==
    # shared executables, asserted through the tracing guard.
    eng_a = fe.engine("a")
    expected = set()
    for r in reqs:
        nnz = tuple(int(r.feature_shards[s].nnz)
                    for s in eng_a.shard_order)
        expected.add(fe.ladder.bucket_shape(r.num_rows, nnz))
    assert fe.cache.compilations <= len(expected) + 1
    fe.cache.assert_max_retraces(max_total=len(expected) + 1, per_fn=1)
    tracing_guard.set_budget(len(expected) + 1)


@pytest.mark.needs_f64
def test_per_model_metrics_do_not_cross_contaminate(rng):
    """Satellite: with two resident models, each engine's stats() reads
    its OWN serving.model.<name>.request_latency_seconds — model a's
    percentiles never fold in model b's observations (the process-wide
    histogram still sums both, documented split)."""
    train = _dataset(rng, n=80)
    gm_a = _game_model(rng, train)
    gm_b = _variant(gm_a, 2.0)
    telemetry.reset()
    telemetry.enable()
    try:
        fe = ServingFrontend({"a": gm_a, "b": gm_b}, dtype=DT,
                             ladder=BucketLadder(**LADDER))
        reqs = _singles(700, 6)
        fe.replay(reqs[:4], model="a", concurrency=2)
        fe.replay(reqs[4:], model="b", concurrency=2)
        st = fe.stats()
        assert st["engines"]["a"]["metrics_label"] == "a"
        assert st["engines"]["a"]["request_latency_seconds"]["count"] == 4
        assert st["engines"]["b"]["request_latency_seconds"]["count"] == 2
        snap = telemetry.snapshot()
        assert snap["counters"]["serving.model.a.requests"] == 4
        assert snap["counters"]["serving.model.b.requests"] == 2
        # process-wide histogram is the sum of both models
        assert snap["histograms"]["serving.request_latency_seconds"][
            "count"] == 6
        # the front-end's end-to-end histogram covers every request too
        assert snap["histograms"][
            "serving.frontend.request_latency_seconds"]["count"] == 6
        assert snap["histograms"][
            "serving.frontend.queue_wait_seconds"]["count"] == 6
    finally:
        telemetry.disable()
        telemetry.reset()


# -- hot swap --------------------------------------------------------------

@pytest.mark.needs_f64
def test_hot_swap_never_drops_and_pins_old_weights(rng):
    """The hot-swap contract: requests admitted BEFORE the swap complete
    on the old weights, byte-identical to pre-swap scoring; requests
    after the swap score on the new weights; nothing drops or errors."""
    train = _dataset(rng, n=80)
    gm_a = _game_model(rng, train)
    gm_b = _variant(gm_a, 5.0)
    ladder = BucketLadder(**LADDER)
    fe = ServingFrontend({"m": gm_a}, dtype=DT, ladder=ladder,
                         config=FrontendConfig(coalesce_window_s=0.0))
    req = _dataset(np.random.default_rng(42), n=3)
    # Reference engines at the SAME ladder: solo requests land in the
    # same bucket shapes, so bitwise identity is well-defined.
    ref_a = StreamingGameScorer(gm_a, dtype=DT, ladder=ladder)
    ref_b = StreamingGameScorer(gm_b, dtype=DT, ladder=ladder)
    bytes_a = ref_a.score(req).tobytes()
    bytes_b = ref_b.score(req).tobytes()
    assert bytes_a != bytes_b

    async def run():
        async with fe:
            pre = await fe.score(req, model="m")
            # Admit in-flight work, THEN swap before the batcher runs:
            # the pinned engine must keep routing it to the old weights.
            inflight = [asyncio.ensure_future(fe.score(req, model="m"))
                        for _ in range(3)]
            await asyncio.sleep(0)  # admission happens; no dispatch yet
            old = fe.swap_model("m", gm_b)
            during = await asyncio.gather(*inflight)
            post = await fe.score(req, model="m")
            return pre, during, post, old

    pre, during, post, old = asyncio.run(run())
    assert pre.tobytes() == bytes_a
    for d in during:  # admitted pre-swap: old weights, byte-identical
        assert d.tobytes() == bytes_a
    assert post.tobytes() == bytes_b
    st = fe.stats()
    assert st["model_swaps"] == 1
    assert st["admitted"] == st["completed"] == 5  # zero drops
    assert st["failed"] == 0
    # the displaced engine still carries its in-flight accounting
    assert old.stats()["requests"] == 4


@pytest.mark.needs_f64
def test_swap_unknown_model_and_duplicate_add(frontend_and_model):
    fe, gm = frontend_and_model
    with pytest.raises(UnknownModelError):
        fe.swap_model("ghost", gm)
    with pytest.raises(FrontendError, match="already resident"):
        fe.add_model("default", gm)
    fe.remove_model("default")
    assert fe.models == ()
    with pytest.raises(UnknownModelError):
        fe.remove_model("default")
