"""Block-streaming feeder (data/block_stream.py): byte-identity of the
native C block path against the pure-python record loop (block-run
boundaries never leak into batches), the bounded-residency prefetch
contract, feeder selection/fallback, and the end-to-end streamed-scoring
regression against one-shot `read_game_dataset` scoring."""

import time

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.avro_reader import (
    iter_game_dataset_batches,
    read_game_dataset,
)
from photon_ml_tpu.data.block_stream import BlockGameStream
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container


def _write_stream_file(path, n, rng, n_features=40, per_row=5,
                       sync_interval=1024, n_users=9, n_items=6,
                       unknown_every=0):
    """Many-block TrainingExampleAvro file; ``unknown_every`` > 0 plants
    entity names no model vocabulary will contain every k-th record."""
    recs = []
    for i in range(n):
        cols = rng.choice(n_features, size=per_row, replace=False)
        user = (f"ghost{i}" if unknown_every and i % unknown_every == 0
                else f"user{i % n_users}")
        recs.append({
            "uid": f"u{i}" if i % 3 else None,
            "label": float(i % 2),
            "features": [
                {"name": f"f{c}", "term": "t" if c % 2 else None,
                 "value": float(rng.normal())} for c in cols],
            "weight": 2.0 if i % 5 == 0 else None,
            "offset": 0.25 if i % 7 == 0 else None,
            "metadataMap": {"userId": user, "itemId": f"item{i % n_items}"},
        })
    write_container(path, schemas.TRAINING_EXAMPLE, recs,
                    sync_interval=sync_interval)
    return recs


def _assert_batches_identical(a, b):
    assert np.array_equal(a.responses, b.responses)
    assert np.array_equal(a.offsets, b.offsets)
    assert np.array_equal(a.weights, b.weights)
    assert a.responses.dtype == b.responses.dtype
    assert (a.uids == b.uids).all()
    assert set(a.feature_shards) == set(b.feature_shards)
    for name in a.feature_shards:
        ma, mb = a.feature_shards[name], b.feature_shards[name]
        assert np.array_equal(ma.data, mb.data)
        assert np.array_equal(ma.indices, mb.indices)
        assert np.array_equal(ma.indptr, mb.indptr)
    assert set(a.id_columns) == set(b.id_columns)
    for t in a.id_columns:
        assert np.array_equal(a.id_columns[t].codes, b.id_columns[t].codes)
        assert np.array_equal(a.id_columns[t].vocabulary,
                              b.id_columns[t].vocabulary)


@pytest.fixture
def stream_file(tmp_path, rng):
    p = tmp_path / "stream.avro"
    _write_stream_file(p, 1000, rng)
    return p


@pytest.fixture
def shard_maps(stream_file):
    from photon_ml_tpu.data.avro_reader import build_index_map

    return {"global": build_index_map(stream_file, ingest_workers=1)}


def _force_no_native(monkeypatch):
    import photon_ml_tpu.native as nat

    monkeypatch.setattr(nat, "_loaded", True)
    monkeypatch.setattr(nat, "_module", None)


@pytest.mark.native_decoder
def test_native_batches_byte_identical_to_python(stream_file, shard_maps):
    """batch_rows=37 never divides the ~85-record blocks, so every batch
    boundary cuts through a block — and the cut must be invisible."""
    native = BlockGameStream(stream_file, ["userId", "itemId"], shard_maps,
                             batch_rows=37, feeder="native",
                             prefetch_depth=2)
    python = BlockGameStream(stream_file, ["userId", "itemId"], shard_maps,
                             batch_rows=37, feeder="python")
    bn, bp = list(native), list(python)
    assert native.decode_path == "native"
    assert python.decode_path == "python"
    assert len(bn) == len(bp) == -(-1000 // 37)
    assert [d.num_rows for d in bn] == [37] * (1000 // 37) + [1000 % 37]
    for a, b in zip(bn, bp):
        _assert_batches_identical(a, b)


@pytest.mark.native_decoder
def test_batches_concatenate_to_one_shot_dataset(stream_file, shard_maps):
    whole, _ = read_game_dataset(stream_file, id_types=["userId"],
                                 feature_shard_maps=shard_maps,
                                 ingest_workers=1)
    batches = list(BlockGameStream(stream_file, ["userId"], shard_maps,
                                   batch_rows=129, feeder="native"))
    assert sum(d.num_rows for d in batches) == whole.num_rows
    np.testing.assert_array_equal(
        np.concatenate([d.responses for d in batches]), whole.responses)
    np.testing.assert_array_equal(
        np.concatenate([d.offsets for d in batches]), whole.offsets)
    np.testing.assert_array_equal(
        np.concatenate([d.weights for d in batches]), whole.weights)
    np.testing.assert_array_equal(
        np.concatenate([d.uids for d in batches]), whole.uids)
    m = sp.vstack([d.feature_shards["global"] for d in batches],
                  format="csr")
    w = whole.feature_shards["global"]
    np.testing.assert_array_equal(m.data, w.data)
    np.testing.assert_array_equal(m.indices, w.indices)
    np.testing.assert_array_equal(m.indptr, w.indptr)
    # Entity vocabularies are batch-local codes but the NAMES round-trip.
    np.testing.assert_array_equal(
        np.concatenate(
            [d.id_columns["userId"].vocabulary[d.id_columns["userId"].codes]
             for d in batches]),
        whole.id_columns["userId"].vocabulary[
            whole.id_columns["userId"].codes])


@pytest.mark.native_decoder
def test_one_shot_read_uses_block_path_and_is_identical(
        stream_file, shard_maps, monkeypatch):
    """Single-process `read_game_dataset` now assembles through the C
    BLOCK decoder (`read_game_dataset_via_blocks` — one decode
    implementation for one-shot AND streamed reads) and the result must
    be byte-identical to the pure-python record loop."""
    from photon_ml_tpu.data.block_stream import read_game_dataset_via_blocks

    via_blocks = read_game_dataset_via_blocks(
        stream_file, ["userId", "itemId"], shard_maps)
    assert via_blocks is not None
    whole, _ = read_game_dataset(stream_file, id_types=["userId", "itemId"],
                                 feature_shard_maps=shard_maps,
                                 ingest_workers=1)
    _assert_batches_identical(via_blocks, whole)
    _force_no_native(monkeypatch)
    python_read, _ = read_game_dataset(
        stream_file, id_types=["userId", "itemId"],
        feature_shard_maps=shard_maps, ingest_workers=1)
    _assert_batches_identical(via_blocks, python_read)


def test_one_shot_block_read_declines_cleanly(stream_file, shard_maps,
                                              monkeypatch):
    from photon_ml_tpu.data.block_stream import read_game_dataset_via_blocks

    _force_no_native(monkeypatch)
    assert read_game_dataset_via_blocks(
        stream_file, ["userId"], shard_maps) is None


def test_auto_falls_back_without_native(stream_file, shard_maps,
                                        monkeypatch):
    native_first = list(BlockGameStream(stream_file, ["userId"], shard_maps,
                                        batch_rows=250))
    _force_no_native(monkeypatch)
    stream = BlockGameStream(stream_file, ["userId"], shard_maps,
                             batch_rows=250)
    assert stream.decode_path == "python"
    fallback = list(stream)
    assert len(fallback) == len(native_first)
    for a, b in zip(native_first, fallback):
        _assert_batches_identical(a, b)


def test_feeder_native_raises_when_unavailable(stream_file, shard_maps,
                                               monkeypatch):
    _force_no_native(monkeypatch)
    with pytest.raises(RuntimeError, match="native"):
        BlockGameStream(stream_file, ["userId"], shard_maps,
                        batch_rows=10, feeder="native")


def test_validation_errors(stream_file, shard_maps):
    with pytest.raises(ValueError, match="batch_rows"):
        BlockGameStream(stream_file, [], shard_maps, batch_rows=0)
    with pytest.raises(ValueError, match="feeder"):
        BlockGameStream(stream_file, [], shard_maps, batch_rows=1,
                        feeder="spark")
    with pytest.raises(ValueError, match="batch_rows"):
        next(iter_game_dataset_batches(stream_file, [], shard_maps,
                                       batch_rows=-1))


@pytest.mark.native_decoder
def test_multi_file_stream_preserves_order(tmp_path, rng):
    from photon_ml_tpu.data.avro_reader import build_index_map

    p1, p2 = tmp_path / "a.avro", tmp_path / "b.avro"
    _write_stream_file(p1, 300, rng)
    _write_stream_file(p2, 170, rng)
    imap = build_index_map([p1, p2], ingest_workers=1)
    maps = {"global": imap}
    whole, _ = read_game_dataset([p1, p2], id_types=["userId"],
                                 feature_shard_maps=maps, ingest_workers=1)
    # batch_rows chosen so one batch SPANS the file boundary.
    batches = list(BlockGameStream([p1, p2], ["userId"], maps,
                                   batch_rows=90, feeder="native"))
    assert sum(d.num_rows for d in batches) == 470
    np.testing.assert_array_equal(
        np.concatenate([d.responses for d in batches]), whole.responses)
    np.testing.assert_array_equal(
        np.concatenate([d.uids for d in batches]), whole.uids)


@pytest.mark.native_decoder
def test_random_access_fetch_matches_streamed_batches(stream_file,
                                                      shard_maps):
    """BlockRandomAccess.fetch_rows reproduces EVERY streamed batch byte
    for byte (batch_rows=37 cuts through every ~85-record block, so
    each fetch must skip a partial head block and stop mid-block)."""
    from photon_ml_tpu.data.block_stream import BlockRandomAccess

    stream = BlockGameStream(stream_file, ["userId", "itemId"], shard_maps,
                             batch_rows=37, feeder="native")
    ra = BlockRandomAccess(stream_file, ["userId", "itemId"], shard_maps,
                           feeder="native")
    assert ra.decode_path == "native"
    assert ra.total_rows == 1000
    row = 0
    for batch in stream:
        got = ra.fetch_rows(row, batch.num_rows)
        _assert_batches_identical(got, batch)
        row += batch.num_rows
    assert ra.rows_fetched == 1000
    assert ra.payload_bytes_read > 0
    assert ra.blocks_decoded > 0


def test_random_access_python_feeder_matches_stream(stream_file,
                                                    shard_maps):
    """The python datum-decode path of fetch_rows is byte-identical to
    the python record-loop stream — the redecode tier works with or
    without the C extension."""
    from photon_ml_tpu.data.block_stream import BlockRandomAccess

    stream = BlockGameStream(stream_file, ["userId"], shard_maps,
                             batch_rows=64, feeder="python")
    ra = BlockRandomAccess(stream_file, ["userId"], shard_maps,
                           feeder="python")
    assert ra.decode_path == "python"
    batches = list(stream)
    # spot-check a head, middle and tail batch (python decode is slow)
    for k in (0, len(batches) // 2, len(batches) - 1):
        got = ra.fetch_rows(64 * k, batches[k].num_rows)
        _assert_batches_identical(got, batches[k])


@pytest.mark.native_decoder
def test_random_access_spans_file_boundary(tmp_path, rng):
    from photon_ml_tpu.data.avro_reader import build_index_map
    from photon_ml_tpu.data.block_stream import BlockRandomAccess

    p1, p2 = tmp_path / "a.avro", tmp_path / "b.avro"
    _write_stream_file(p1, 300, rng)
    _write_stream_file(p2, 170, rng)
    maps = {"global": build_index_map([p1, p2], ingest_workers=1)}
    batches = list(BlockGameStream([p1, p2], ["userId"], maps,
                                   batch_rows=90, feeder="native"))
    ra = BlockRandomAccess([p1, p2], ["userId"], maps, feeder="native")
    assert ra.total_rows == 470
    # batch index 3 covers rows [270, 360): spans the 300-row boundary
    got = ra.fetch_rows(270, 90)
    _assert_batches_identical(got, batches[3])


def test_random_access_validates_ranges_and_feeder(stream_file,
                                                   shard_maps,
                                                   monkeypatch):
    from photon_ml_tpu.data.block_stream import BlockRandomAccess

    ra = BlockRandomAccess(stream_file, [], shard_maps, feeder="python")
    with pytest.raises(ValueError, match="n_rows"):
        ra.fetch_rows(0, 0)
    with pytest.raises(ValueError, match="outside"):
        ra.fetch_rows(990, 20)
    with pytest.raises(ValueError, match="feeder"):
        BlockRandomAccess(stream_file, [], shard_maps, feeder="turbo")
    _force_no_native(monkeypatch)
    with pytest.raises(RuntimeError, match="native"):
        BlockRandomAccess(stream_file, [], shard_maps, feeder="native")


@pytest.mark.native_decoder
def test_single_partial_batch_when_batch_rows_exceeds_input(stream_file,
                                                            shard_maps):
    batches = list(BlockGameStream(stream_file, ["userId"], shard_maps,
                                   batch_rows=10_000, feeder="native"))
    assert [d.num_rows for d in batches] == [1000]


@pytest.mark.native_decoder
def test_prefetch_peak_residency_bounded(stream_file, shard_maps):
    """A deliberately slow consumer lets the prefetch thread run as far
    ahead as it ever can; resident batches must stay bounded by
    depth (queue) + 1 (producer's hand) + 1 (consumer's hand)."""
    for depth in (1, 3):
        stream = BlockGameStream(stream_file, ["userId"], shard_maps,
                                 batch_rows=50, feeder="native",
                                 prefetch_depth=depth)
        got = 0
        for _ in stream:
            got += 1
            if got <= 3:
                # Give the producer ample time to fill the queue and
                # block on it — the worst case for residency.
                time.sleep(0.05)
        assert got == 20
        assert 0 < stream.peak_resident_batches <= depth + 2, \
            stream.stats()


@pytest.mark.native_decoder
def test_corrupt_block_payload_names_file(tmp_path, rng, shard_maps):
    from photon_ml_tpu.data.shard_planner import scan_container_blocks

    p = tmp_path / "bad.avro"
    _write_stream_file(p, 800, rng)
    index = scan_container_blocks(p)
    assert len(index.blocks) >= 3
    block = index.blocks[1]
    raw = bytearray(p.read_bytes())

    def varint_len(off):
        k = 0
        while raw[off + k] & 0x80:
            k += 1
        return k + 1

    payload_start = block.offset + varint_len(block.offset)
    payload_start += varint_len(payload_start)
    for i in range(8):
        raw[payload_start + 4 + i] ^= 0xFF
    p.write_bytes(bytes(raw))

    stream = BlockGameStream(p, [], shard_maps, batch_rows=64,
                             feeder="native", prefetch_depth=2)
    with pytest.raises(ValueError, match="bad.avro"):
        list(stream)


# -- streamed scoring regression (the --stream contract) -------------------


def _scoring_model_and_maps(rng):
    """A device-scorable GAME model (fixed + per-user RE + MF) plus the
    feature shard maps an Avro scoring input joins through."""
    from photon_ml_tpu.data.game_data import GameDataset
    from photon_ml_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.models import (
        Coefficients,
        FixedEffectModel,
        GameModel,
        LogisticRegressionModel,
        MatrixFactorizationModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.types import TaskType

    n, n_users, n_items = 90, 9, 6
    x = rng.normal(0, 1, (n, 6))
    user_x = np.hstack([rng.normal(0, 1, (n, 2)), np.ones((n, 1))])
    train = GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"global": sp.csr_matrix(x),
                        "user": sp.csr_matrix(user_x)},
        ids={"userId": rng.integers(0, n_users, n).astype(str),
             "itemId": rng.integers(0, n_items, n).astype(str)})
    ds = build_random_effect_dataset(
        train, RandomEffectDataConfiguration("userId", "user"),
        intercept_col=2)
    re = RandomEffectModel.zeros_like_dataset(ds, dtype=jnp.float64)
    re = re.with_coefs([jnp.asarray(rng.normal(0, 1, np.asarray(c).shape))
                        for c in re.local_coefs])
    fe = FixedEffectModel(
        LogisticRegressionModel(Coefficients(
            jnp.asarray(rng.normal(0, 1, 6)))), "global")
    mf = MatrixFactorizationModel(
        "userId", "itemId",
        jnp.asarray(rng.normal(0, 1, (n_users, 3))),
        jnp.asarray(rng.normal(0, 1, (n_items, 3))),
        np.unique(train.id_columns["userId"].vocabulary),
        np.unique(train.id_columns["itemId"].vocabulary))
    model = GameModel({"fixed": fe, "perUser": re, "mf": mf},
                      TaskType.LOGISTIC_REGRESSION)
    maps = {
        "global": IndexMap({feature_key(f"g{j}"): j for j in range(6)}),
        "user": IndexMap({feature_key(f"w{j}"): j for j in range(3)}),
    }
    return model, maps


def _write_scoring_file(path, rng, n=140, n_users=9, n_items=6):
    recs = []
    for i in range(n):
        feats = [{"name": f"g{j}", "term": None,
                  "value": float(rng.normal())} for j in range(6)]
        feats += [{"name": f"w{j}", "term": None,
                   "value": float(rng.normal())} for j in range(3)]
        # ~1 in 6 rows carries an entity no model vocabulary contains —
        # it must score exactly 0 on RE/MF terms, streamed or not.
        user = f"ghost{i}" if i % 6 == 0 else f"user{i % n_users}"
        recs.append({
            "uid": f"r{i}", "label": float(i % 2), "features": feats,
            "weight": None, "offset": 0.5 if i % 4 == 0 else None,
            "metadataMap": {"userId": user, "itemId": f"item{i % n_items}"},
        })
    write_container(path, schemas.TRAINING_EXAMPLE, recs,
                    sync_interval=512)  # many small blocks


@pytest.mark.native_decoder
@pytest.mark.needs_f64
def test_streamed_scoring_byte_identical_to_one_shot(tmp_path, rng):
    """--stream's pipeline (C feeder, prefetch on) must reproduce one-shot
    `read_game_dataset` + engine scoring BYTE-identically, including
    across block-run boundaries and with unknown entities in-stream."""
    from photon_ml_tpu.serving import BucketLadder, StreamingGameScorer

    model, maps = _scoring_model_and_maps(rng)
    p = tmp_path / "score.avro"
    _write_scoring_file(p, rng)

    engine = StreamingGameScorer(model, dtype=jnp.float64,
                                 ladder=BucketLadder(min_rows=8,
                                                     max_rows=64))
    scored = engine.score_container_stream(
        p, id_types=["userId", "itemId"], feature_shard_maps=maps,
        batch_rows=33, feeder="native", prefetch_depth=2)
    streamed_scores, streamed_rows = [], 0
    for ds, scores in scored:
        assert len(scores) == ds.num_rows
        streamed_scores.append(scores)
        streamed_rows += ds.num_rows
    assert scored.stream.decode_path == "native"
    assert streamed_rows == 140

    whole, _ = read_game_dataset(p, id_types=["userId", "itemId"],
                                 feature_shard_maps=maps, ingest_workers=1)
    one_shot = engine.score(whole)
    np.testing.assert_array_equal(np.concatenate(streamed_scores),
                                  one_shot)


@pytest.mark.needs_f64
def test_streamed_scoring_python_feeder_matches_native_path(tmp_path, rng,
                                                            monkeypatch):
    """The same scoring stream through the python fallback produces the
    same bytes — the feeder choice can never change a score."""
    from photon_ml_tpu.serving import BucketLadder, StreamingGameScorer

    model, maps = _scoring_model_and_maps(rng)
    p = tmp_path / "score.avro"
    _write_scoring_file(p, rng)
    engine = StreamingGameScorer(model, dtype=jnp.float64,
                                 ladder=BucketLadder(min_rows=8,
                                                     max_rows=64))

    def scores_with(feeder, prefetch):
        out = [s for _, s in engine.score_container_stream(
            p, id_types=["userId", "itemId"], feature_shard_maps=maps,
            batch_rows=33, feeder=feeder, prefetch_depth=prefetch)]
        return np.concatenate(out)

    auto = scores_with("auto", 2)
    python = scores_with("python", 0)
    np.testing.assert_array_equal(auto, python)
