"""GAME coordinate-descent integration tests on synthetic GLMix data —
the analog of the reference's CoordinateDescentTest + GameEstimatorTest
(using generated fixed+random effect data like GameTestUtils does).
"""

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.algorithm import (
    CoordinateDescent,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.evaluation import build_evaluator
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.types import TaskType


def make_glmix_data(rng, n=400, d=6, n_users=12, user_strength=2.0):
    """Logistic data with a global linear effect + per-user intercept shift."""
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    w_global = rng.normal(0, 1, d)
    users = rng.integers(0, n_users, n)
    user_bias = rng.normal(0, user_strength, n_users)
    z = x @ w_global + user_bias[users]
    y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(float)

    user_feats = sp.csr_matrix(np.ones((n, 1)))  # per-user intercept shard
    data = GameDataset.build(
        responses=y,
        feature_shards={"global": sp.csr_matrix(x), "user": user_feats},
        ids={"userId": np.asarray([f"u{u}" for u in users])},
    )
    return data, w_global, user_bias, users


def build_coordinates(data, fe_cfg=None, re_cfg=None):
    fe_cfg = fe_cfg or GLMOptimizationConfiguration(
        max_iterations=50, tolerance=1e-8, regularization_weight=0.1,
        regularization_context=RegularizationContext(RegularizationType.L2),
    )
    re_cfg = re_cfg or GLMOptimizationConfiguration(
        max_iterations=30, tolerance=1e-8, regularization_weight=0.1,
        regularization_context=RegularizationContext(RegularizationType.L2),
    )
    re_data = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "user"),
        intercept_col=0)
    fixed = FixedEffectCoordinate(
        name="fixed", data=data, feature_shard_id="global",
        task_type=TaskType.LOGISTIC_REGRESSION, config=fe_cfg)
    per_user = RandomEffectCoordinate(
        name="perUser", dataset=re_data,
        task_type=TaskType.LOGISTIC_REGRESSION, config=re_cfg)
    return {"fixed": fixed, "perUser": per_user}


def test_fixed_effect_only_descent(rng):
    data, w_global, _, _ = make_glmix_data(rng, user_strength=0.0)
    coords = build_coordinates(data)
    cd = CoordinateDescent({"fixed": coords["fixed"]},
                           TaskType.LOGISTIC_REGRESSION)
    res = cd.run(num_iterations=2)
    fe = res.model.get_model("fixed")
    w = np.asarray(fe.glm.coefficients.means)
    corr = np.corrcoef(w, w_global)[0, 1]
    assert corr > 0.9
    h = res.objective_history
    assert h[-1] <= h[0] + 1e-5 * abs(h[0])  # f32 noise margin


def test_glmix_descent_improves_and_recovers_user_bias(rng):
    data, w_global, user_bias, users = make_glmix_data(rng)
    coords = build_coordinates(data)
    cd = CoordinateDescent(coords, TaskType.LOGISTIC_REGRESSION)
    res = cd.run(num_iterations=3)

    # Objective decreases across coordinate updates.
    h = res.objective_history
    assert h[-1] < h[0]
    # Monotone non-increasing up to tiny numerical noise.
    assert all(h[i + 1] <= h[i] + 1e-4 * abs(h[i]) for i in range(len(h) - 1))

    # The per-user random intercepts should correlate with the true biases.
    re_model = res.model.get_model("perUser")
    m = re_model.model_matrix().toarray()[:, 0]
    vocab = re_model.vocabulary
    learned = np.asarray(
        [m[np.flatnonzero(vocab == f"u{u}")[0]]
         for u in range(len(user_bias))])
    corr = np.corrcoef(learned, user_bias)[0, 1]
    assert corr > 0.8, f"user-bias corr {corr}"


def test_random_effect_scoring_device_equals_host(rng):
    """The device scatter path and the host model_matrix path must agree —
    this pins the projected-space round trip
    (RandomEffectModelInProjectedSpace conversion semantics)."""
    data, *_ = make_glmix_data(rng)
    coords = build_coordinates(data)
    cd = CoordinateDescent(coords, TaskType.LOGISTIC_REGRESSION)
    res = cd.run(num_iterations=1)
    re_coord = coords["perUser"]
    re_model = res.model.get_model("perUser")
    device_scores = np.asarray(re_coord.score(re_model))
    host_scores = re_model.score_numpy(data)
    np.testing.assert_allclose(device_scores, host_scores, atol=1e-5)


def test_validation_tracking_selects_best(rng):
    data, *_ = make_glmix_data(rng, n=500)
    train = data.subset(np.arange(400))
    valid = data.subset(np.arange(400, 500))
    coords = build_coordinates(train)
    cd = CoordinateDescent(
        coords, TaskType.LOGISTIC_REGRESSION,
        validation_data=valid,
        validation_evaluators=[build_evaluator("AUC"),
                               build_evaluator("LOGISTIC_LOSS")])
    res = cd.run(num_iterations=2)
    assert len(res.validation_history) == 2
    assert res.best_metric is not None
    assert res.best_metric >= 0.5  # AUC no worse than random
    for metrics in res.validation_history:
        assert set(metrics) == {"AUC", "LOGISTIC_LOSS"}


def test_warm_start_resumes(rng):
    data, *_ = make_glmix_data(rng)
    coords = build_coordinates(data)
    cd = CoordinateDescent(coords, TaskType.LOGISTIC_REGRESSION)
    res1 = cd.run(num_iterations=1)
    res2 = cd.run(num_iterations=1, initial_model=res1.model)
    assert res2.objective_history[-1] <= res1.objective_history[-1] + 1e-6


@pytest.mark.slow
def test_cd_objective_invariant_across_mesh_sizes(rng):
    """Sharding invariance — the BASELINE north-star's chip-scaling
    property testable without a pod: the SAME GLMix descent on 1/2/4/8
    virtual devices produces the same objective trajectory (row padding,
    entity padding, and the psum'd reductions are all exact no-ops on the
    math)."""
    from photon_ml_tpu.parallel import make_mesh
    from tests.conftest import gold

    data, *_ = make_glmix_data(rng, n=300)
    histories = {}
    for n_dev in (1, 2, 4, 8):
        mesh = make_mesh(n_dev)
        fe_cfg = GLMOptimizationConfiguration(
            max_iterations=20, tolerance=1e-8, regularization_weight=0.1,
            regularization_context=RegularizationContext(
                RegularizationType.L2))
        re_data = build_random_effect_dataset(
            data, RandomEffectDataConfiguration("userId", "user"),
            intercept_col=0)
        coords = {
            "fixed": FixedEffectCoordinate(
                name="fixed", data=data, feature_shard_id="global",
                task_type=TaskType.LOGISTIC_REGRESSION, config=fe_cfg,
                mesh=mesh),
            "perUser": RandomEffectCoordinate(
                name="perUser", dataset=re_data,
                task_type=TaskType.LOGISTIC_REGRESSION, config=fe_cfg,
                mesh=mesh),
        }
        cd = CoordinateDescent(coords, TaskType.LOGISTIC_REGRESSION)
        histories[n_dev] = cd.run(num_iterations=2).objective_history
    base = histories[1]
    for n_dev, h in histories.items():
        # Reduction reassociation across shards perturbs low bits, which
        # the iterative solver amplifies to ~solver-tolerance differences;
        # a padding/sharding BUG shows up orders of magnitude larger.
        np.testing.assert_allclose(h, base, rtol=gold(1e-5, f32_floor=1e-3),
                                   err_msg=f"mesh size {n_dev}")
