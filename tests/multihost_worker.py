"""Worker program for the two-process multihost test (run via subprocess).

Each process owns 2 virtual CPU devices; together they form a 4-device
global mesh. Exercises initialize_multihost's explicit-coordinator path
(the analog of a manual multi-host TPU launch) plus a cross-host psum.
"""

import os
import sys


def main():
    # Per-process device config must land before jax initializes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from photon_ml_tpu.parallel import initialize_multihost, is_primary_host

    ok = initialize_multihost()  # COORDINATOR_ADDRESS etc. from env
    assert ok, "initialize_multihost returned False under a launcher config"
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    assert jax.local_device_count() == 2

    pid = jax.process_index()
    assert is_primary_host() == (pid == 0)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

    # Global arange(8) sharded 2-per-device across BOTH processes; the
    # psum must see every host's rows.
    global_shape = (8,)
    sharding = NamedSharding(mesh, P("data"))
    full = np.arange(8, dtype=np.float32)

    def local_cb(index):
        return full[index]

    arr = jax.make_array_from_callback(global_shape, sharding, local_cb)

    @jax.jit
    def total(a):
        return jnp.sum(a)

    result = float(total(arr))
    assert result == float(full.sum()), result

    # Cross-host gradient-style reduction through shard_map psum.
    from jax.experimental.shard_map import shard_map

    f = shard_map(
        lambda x: jax.lax.psum(jnp.sum(x), "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P())
    assert float(f(arr)) == float(full.sum())

    print(f"MULTIHOST_OK process={pid} total={result}")
    sys.stdout.flush()


if __name__ == "__main__":
    main()
