"""f32-vs-f64 parity of the optimizer and coordinate-descent outcomes
(SURVEY hard-part 3: the TPU executes f32; the reference's Breeze runs f64).

These tests build the SAME problem in both dtypes and assert that the f32
path converges for the same reason, to the same objective (relative 1e-4)
and coefficients (1e-3) as f64 — the level at which f32 rounding in the
L-BFGS curvature pairs / CG residuals would surface as divergence.

Second CI config: PHOTON_ML_TPU_TEST_F32=1 runs the whole conftest without
x64, executing the optimizer/coordinate suites in pure f32 (see
docs/F32_PARITY.md for the measured parity table). Under that mode the
f64 halves here are skipped (f64 arrays don't exist without x64).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.ops import GLMObjective
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.glm_objective import make_batch
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optimization.solver import solve_glm
from photon_ml_tpu.types import TaskType

X64 = jax.config.jax_enable_x64

needs_f64 = pytest.mark.skipif(
    not X64, reason="f64 half requires x64 mode (default CI config)")


def _problem(rng, n=4000, d=24, task=TaskType.LOGISTIC_REGRESSION):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    w = rng.normal(0, 0.5, d)
    z = x @ w
    if task == TaskType.LOGISTIC_REGRESSION:
        y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(float)
    elif task == TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(0.3 * z, None, 3.0))).astype(float)
    else:
        y = z + rng.normal(0, 0.1, n)
    return x, y


def _solve(x, y, task, dtype, optimizer=OptimizerType.LBFGS, lam=1.0,
           max_iter=100, tol=1e-6):
    batch = make_batch(DenseFeatures(jnp.asarray(x, dtype)),
                       jnp.asarray(y, dtype))
    config = GLMOptimizationConfiguration(
        max_iterations=max_iter, tolerance=tol, regularization_weight=lam,
        regularization_context=RegularizationContext(RegularizationType.L2),
        optimizer_type=optimizer)
    objective = GLMObjective(loss_for_task(task))
    return solve_glm(objective, batch, config,
                     jnp.zeros(x.shape[1], dtype))


@needs_f64
@pytest.mark.parametrize("task,optimizer", [
    (TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS),
    (TaskType.LINEAR_REGRESSION, OptimizerType.LBFGS),
    (TaskType.POISSON_REGRESSION, OptimizerType.LBFGS),
    (TaskType.LOGISTIC_REGRESSION, OptimizerType.TRON),
    (TaskType.LINEAR_REGRESSION, OptimizerType.TRON),
])
def test_solver_f32_matches_f64(rng, task, optimizer):
    x, y = _problem(rng, task=task)
    r64 = _solve(x, y, task, jnp.float64, optimizer)
    r32 = _solve(x, y, task, jnp.float32, optimizer)

    assert int(r64.reason) != 0 and int(r32.reason) != 0  # both converged
    v64, v32 = float(r64.value), float(r32.value)
    assert abs(v32 - v64) <= 1e-4 * abs(v64), (v32, v64)
    np.testing.assert_allclose(np.asarray(r32.x, np.float64),
                               np.asarray(r64.x), rtol=2e-3, atol=2e-3)


@needs_f64
def test_owlqn_f32_matches_f64(rng):
    x, y = _problem(rng)
    cfg = dict(optimizer=OptimizerType.LBFGS, lam=0.5)
    batch64 = make_batch(DenseFeatures(jnp.asarray(x, jnp.float64)),
                         jnp.asarray(y, jnp.float64))
    batch32 = make_batch(DenseFeatures(jnp.asarray(x, jnp.float32)),
                         jnp.asarray(y, jnp.float32))
    config = GLMOptimizationConfiguration(
        max_iterations=150, tolerance=1e-6, regularization_weight=0.5,
        regularization_context=RegularizationContext(
            RegularizationType.ELASTIC_NET, elastic_net_alpha=0.5))
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    r64 = solve_glm(obj, batch64, config, jnp.zeros(x.shape[1], jnp.float64))
    r32 = solve_glm(obj, batch32, config, jnp.zeros(x.shape[1], jnp.float32))
    v64, v32 = float(r64.value), float(r32.value)
    assert abs(v32 - v64) <= 2e-4 * abs(v64), (v32, v64)
    # Same sparsity pattern from the L1 orthant steps.
    z64 = np.abs(np.asarray(r64.x)) < 1e-6
    z32 = np.abs(np.asarray(r32.x)) < 1e-6
    assert (z64 == z32).mean() >= 0.9


def _glmix_cd(rng, dtype, n=2000, d=12, n_users=40):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    users = rng.integers(0, n_users, n)
    y = (rng.random(n) < 0.5).astype(float)
    data = GameDataset.build(
        responses=y,
        feature_shards={"global": sp.csr_matrix(x),
                        "user": sp.csr_matrix(
                            np.hstack([rng.normal(0, 1, (n, 2)),
                                       np.ones((n, 1))]))},
        ids={"userId": users.astype(str)})

    from photon_ml_tpu.algorithm import (
        CoordinateDescent,
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )

    l2 = RegularizationContext(RegularizationType.L2)
    coords = {
        "fixed": FixedEffectCoordinate(
            name="fixed", data=data, feature_shard_id="global",
            task_type=TaskType.LOGISTIC_REGRESSION,
            config=GLMOptimizationConfiguration(
                max_iterations=30, tolerance=1e-6, regularization_weight=1.0,
                regularization_context=l2),
            dtype=dtype),
        "perUser": RandomEffectCoordinate(
            name="perUser",
            dataset=build_random_effect_dataset(
                data, RandomEffectDataConfiguration("userId", "user"),
                intercept_col=2, dtype=dtype),
            task_type=TaskType.LOGISTIC_REGRESSION,
            config=GLMOptimizationConfiguration(
                max_iterations=15, tolerance=1e-6, regularization_weight=1.0,
                regularization_context=l2)),
    }
    cd = CoordinateDescent(coords, TaskType.LOGISTIC_REGRESSION)
    return cd.run(num_iterations=3, seed=5)


@needs_f64
@pytest.mark.slow
def test_coordinate_descent_f32_matches_f64(rng):
    """Full GLMix coordinate descent: the f32 objective trajectory must
    track f64 at ~1e-4 relative per update, and both must be monotone
    non-increasing to the same degree."""
    res64 = _glmix_cd(np.random.default_rng(11), jnp.float64)
    res32 = _glmix_cd(np.random.default_rng(11), jnp.float32)
    h64 = np.asarray(res64.objective_history)
    h32 = np.asarray(res32.objective_history)
    assert len(h64) == len(h32) == 6
    np.testing.assert_allclose(h32, h64, rtol=2e-4)
    assert h64[-1] <= h64[0] and h32[-1] <= h32[0]


def test_solvers_run_in_current_dtype(rng):
    """Mode-agnostic smoke: whatever dtype the CI config dictates (f32 in
    the PHOTON_ML_TPU_TEST_F32=1 config), the solvers converge to a finite
    optimum with a real convergence reason."""
    x, y = _problem(rng, n=1500, d=12)
    for optimizer in (OptimizerType.LBFGS, OptimizerType.TRON):
        r = _solve(x, y, TaskType.LOGISTIC_REGRESSION, jnp.float32,
                   optimizer)
        assert np.isfinite(float(r.value))
        assert int(r.reason) != 0
