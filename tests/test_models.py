"""Model-layer unit tests (reference: model/CoefficientsTest,
GameModelTest, MatrixFactorizationModelTest patterns)."""

import numpy as np
import jax.numpy as jnp
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    LinearRegressionModel,
    LogisticRegressionModel,
    MatrixFactorizationModel,
    PoissonRegressionModel,
    model_for_task,
)
from photon_ml_tpu.models.glm import model_class_by_name
from photon_ml_tpu.types import TaskType


def test_coefficients_score_and_zeros():
    c = Coefficients(jnp.asarray([1.0, -2.0, 0.5]))
    np.testing.assert_allclose(
        c.compute_score(jnp.asarray([[1.0, 1.0, 2.0]])), [0.0])
    z = Coefficients.zeros(3)
    assert z.num_features == 3 and float(z.means_norm) == 0.0
    assert not c.is_close_to(z)


def test_glm_means_and_classes():
    x = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
    c = Coefficients(jnp.asarray([2.0, -2.0]))
    logit = LogisticRegressionModel(c)
    np.testing.assert_allclose(
        np.asarray(logit.compute_mean(x)),
        [1 / (1 + np.exp(-2)), 1 / (1 + np.exp(2))], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(logit.predict_class(x)), [1.0, 0.0])
    lin = LinearRegressionModel(c)
    np.testing.assert_allclose(np.asarray(lin.compute_mean(x, 1.0)),
                               [3.0, -1.0])
    pois = PoissonRegressionModel(c)
    np.testing.assert_allclose(np.asarray(pois.compute_mean(x)),
                               [np.exp(2), np.exp(-2)], rtol=1e-6)
    assert model_for_task(TaskType.LOGISTIC_REGRESSION) is \
        LogisticRegressionModel
    assert model_class_by_name("LogisticRegressionModel") is \
        LogisticRegressionModel


def _tiny_game_data():
    x = np.asarray([[1.0, 2.0], [0.0, 1.0], [1.0, 0.0]])
    return GameDataset.build(
        responses=np.asarray([1.0, 0.0, 1.0]),
        feature_shards={"s": sp.csr_matrix(x)},
        ids={"userId": np.asarray(["a", "b", "a"]),
             "itemId": np.asarray(["x", "x", "y"])},
        offsets=np.asarray([0.1, 0.2, 0.3]),
    )


def test_fixed_effect_model_scores():
    data = _tiny_game_data()
    fe = FixedEffectModel(
        LogisticRegressionModel(Coefficients(jnp.asarray([1.0, -1.0]))), "s")
    np.testing.assert_allclose(np.asarray(fe.score(data)), [-1.0, -1.0, 1.0])
    np.testing.assert_allclose(fe.score_numpy(data), [-1.0, -1.0, 1.0])


def test_mf_model_scores_and_unseen_entities():
    data = _tiny_game_data()
    mf = MatrixFactorizationModel(
        row_effect_type="userId", col_effect_type="itemId",
        row_factors=jnp.asarray([[1.0, 0.0], [0.0, 2.0]]),  # a, b
        col_factors=jnp.asarray([[3.0, 1.0]]),  # only "x"; "y" unseen
        row_vocabulary=np.asarray(["a", "b"]),
        col_vocabulary=np.asarray(["x"]))
    # rows: (a,x)=3, (b,x)=2, (a,y)=0 (unseen item)
    np.testing.assert_allclose(mf.score_numpy(data), [3.0, 2.0, 0.0])


def test_game_model_additive_score_and_update():
    data = _tiny_game_data()
    fe = FixedEffectModel(
        LogisticRegressionModel(Coefficients(jnp.asarray([1.0, -1.0]))), "s")
    gm = GameModel({"fixed": fe}, TaskType.LOGISTIC_REGRESSION)
    np.testing.assert_allclose(gm.score(data), [-1.0, -1.0, 1.0])
    mean = gm.predict_mean(data)
    np.testing.assert_allclose(
        mean, 1 / (1 + np.exp(-(np.asarray([-1.0, -1.0, 1.0]) +
                                data.offsets))))
    fe2 = FixedEffectModel(
        LogisticRegressionModel(Coefficients(jnp.asarray([0.0, 0.0]))), "s")
    gm2 = gm.update_model("fixed", fe2)
    np.testing.assert_allclose(gm2.score(data), 0.0)
    with pytest.raises(KeyError):
        gm.update_model("nope", fe2)
