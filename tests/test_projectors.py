"""Projector tests: Gaussian random projection, index-map projector, and the
projected-space random-effect training path.

Mirrors the reference's ProjectionMatrixTest / IndexMapProjectorTest and the
projected-space coordinate integration tests.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.algorithm import RandomEffectCoordinate
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.projector import (
    IndexMapProjector,
    ProjectionMatrix,
    build_random_effect_projector,
)
from photon_ml_tpu.types import TaskType

import jax


def test_gaussian_projection_matrix_semantics():
    k, d = 8, 100
    p = ProjectionMatrix.gaussian(k, d, intercept_col=d - 1, seed=3)
    # Intercept pass-through row appended: [k+1, d].
    assert p.matrix.shape == (k + 1, d)
    assert p.projected_space_dimension == k + 1
    # Pass-through: projecting a vector preserves the intercept exactly.
    x = np.random.default_rng(0).normal(0, 1, d)
    x[d - 1] = 1.0
    z = p.project_features(x[None, :])[0]
    assert z.shape == (k + 1,)
    np.testing.assert_allclose(z[-1], 1.0)
    # Entries scaled by 1/k (reference: std = projectedSpaceDimension) and
    # clipped to [-1, 1].
    body = p.matrix[:k, : d - 1]
    assert np.abs(body).max() <= 1.0
    assert np.std(body) == pytest.approx(1.0 / k, rel=0.2)
    # Back-projection is the transpose map.
    gamma = np.random.default_rng(1).normal(0, 1, k + 1)
    np.testing.assert_allclose(
        p.project_coefficients(gamma), p.matrix.T @ gamma)
    # Score equivalence: x . (P^T gamma) == (P x) . gamma.
    np.testing.assert_allclose(
        x @ p.project_coefficients(gamma), z @ gamma)


def test_index_map_projector_roundtrip():
    cols = np.asarray([2, 5, 7])
    proj = IndexMapProjector(cols=cols, num_global_features=10)
    x = sp.random(4, 10, density=0.5, random_state=0, format="csr")
    np.testing.assert_allclose(
        proj.project_features(x), x.toarray()[:, cols])
    local = np.asarray([1.0, -2.0, 3.0])
    glob = proj.project_coefficients(local)
    assert glob.shape == (10,)
    np.testing.assert_allclose(glob[cols], local)
    assert np.count_nonzero(glob) == 3


def test_projector_selection():
    assert build_random_effect_projector("INDEX_MAP", 10) is None
    assert build_random_effect_projector("IDENTITY", 10) is None
    p = build_random_effect_projector("RANDOM=4", 10)
    assert isinstance(p, ProjectionMatrix)
    assert p.matrix.shape == (4, 10)
    with pytest.raises(ValueError):
        build_random_effect_projector("PALDB", 10)


def _projected_fixture(rng, n=120, d=24, n_users=6, k=4):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    users = rng.integers(0, n_users, n)
    bias = rng.normal(0, 2.0, n_users)
    z = 0.3 * x[:, 0] + bias[users]
    y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(float)
    data = GameDataset.build(
        responses=y,
        feature_shards={"shard": sp.csr_matrix(x)},
        ids={"userId": np.asarray([f"u{u}" for u in users])})
    cfg = RandomEffectDataConfiguration(
        "userId", "shard", projector_type=f"RANDOM={k}")
    ds = build_random_effect_dataset(data, cfg, intercept_col=d - 1)
    return data, ds, k


def test_projected_dataset_blocks_are_latent(rng):
    data, ds, k = _projected_fixture(rng)
    assert ds.projection is not None
    k1 = ds.projection.projected_space_dimension
    assert k1 == k + 1  # + intercept pass-through
    for b in ds.blocks:
        # All blocks share the latent width (single size class).
        assert int(np.asarray(b.feat_idx).max()) == k1 - 1
    # Latent features equal the projection of the original rows.
    mat = data.feature_shards["shard"].toarray()
    b = ds.blocks[0]
    for e in range(b.num_entities):
        for r in range(b.n_pad):
            gr = int(b.row_ids[e, r])
            if gr == ds.n_rows:
                continue
            np.testing.assert_allclose(
                np.asarray(b.x[e, r])[:k1],
                ds.projection.project_features(mat[gr][None, :])[0],
                rtol=1e-5, atol=1e-6)


def test_projected_random_effect_training_and_back_projection(rng):
    data, ds, k = _projected_fixture(rng)
    coord = RandomEffectCoordinate(
        name="perUser", dataset=ds,
        task_type=TaskType.LOGISTIC_REGRESSION,
        config=GLMOptimizationConfiguration(
            max_iterations=50, tolerance=1e-9, regularization_weight=1e-3,
            regularization_context=RegularizationContext(
                RegularizationType.L2)))
    model = coord.initialize_model()
    assert model.projection is ds.projection
    model, _ = coord.update_model(model, None, jax.random.key(0))

    # Training in the latent space moved the model.
    assert any(float(np.abs(np.asarray(c)).max()) > 0
               for c in model.local_coefs)

    # Back-projected global model scores == latent scores on the same rows.
    latent_scores = np.asarray(coord.score(model))
    global_scores = model.score_numpy(data)
    np.testing.assert_allclose(latent_scores, global_scores,
                               rtol=1e-4, atol=1e-5)

    # model_matrix rows live in the global space.
    m = model.model_matrix()
    assert m.shape == (len(ds.vocabulary), data.feature_shards["shard"].shape[1])
    assert abs(m).sum() > 0
