"""data/shard_cache.py: streamed ingest -> exact assembly / padded device
cache with LRU spill. The assembly contract (bitwise equality with the
one-shot `fixed_effect_batch`) is what makes `--stream-train` write a
byte-identical model to the one-shot driver.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.shard_cache import (
    DeviceShardCache,
    assemble_fixed_effect_batch,
    encode_spill,
    restore_spilled_features,
)
from photon_ml_tpu.ops.features import padded_csr_arrays


class FakeStream:
    """GameDataset batches cut from one host matrix — the BlockGameStream
    shape without Avro (decode identity is test_block_stream's job)."""

    def __init__(self, X, y, batch_rows, offsets=None, weights=None):
        self.X = sp.csr_matrix(X)
        self.y = np.asarray(y, float)
        self.offsets = offsets
        self.weights = weights
        self.batch_rows = batch_rows

    def __iter__(self):
        n = self.X.shape[0]
        for s in range(0, n, self.batch_rows):
            e = min(n, s + self.batch_rows)
            yield GameDataset.build(
                responses=self.y[s:e], feature_shards={"g": self.X[s:e]},
                offsets=None if self.offsets is None else self.offsets[s:e],
                weights=None if self.weights is None else self.weights[s:e])

    def stats(self):
        return {"decode_path": "fake", "batches": -1}


@pytest.fixture
def problem(rng):
    n, d = 517, 37
    X = sp.random(n, d, density=0.08, random_state=5, format="csr")
    X.data[:] = rng.normal(0, 1, X.nnz)
    y = (rng.random(n) < 0.5).astype(float)
    off = rng.normal(0, 0.2, n)
    w = rng.gamma(1.0, 1.0, n)
    return X, y, off, w


def _one_shot_batch(X, y, off, w, dtype=jnp.float32):
    data = GameDataset.build(responses=y, feature_shards={"g": X},
                             offsets=off, weights=w)
    return data.fixed_effect_batch("g", dtype=dtype)


def _tobytes(a):
    return np.asarray(a).tobytes()


@pytest.mark.parametrize("batch_rows", [64, 33, 517, 1000])
def test_assembly_bitwise_equals_one_shot_sparse(problem, batch_rows):
    """CSR regime (density < threshold): values/col_ids/row_ids and the
    row columns must be the one-shot arrays bit for bit, for aligned,
    non-aligned, exact and oversized batch_rows."""
    X, y, off, w = problem
    ref = _one_shot_batch(X, y, off, w)
    shim = assemble_fixed_effect_batch(
        FakeStream(X, y, batch_rows, off, w), "g")
    got = shim.fixed_effect_batch("g")
    assert type(got.features) is type(ref.features)
    for name in ("values", "col_ids", "row_ids"):
        assert _tobytes(getattr(got.features, name)) == \
            _tobytes(getattr(ref.features, name)), name
    for name in ("labels", "offsets", "weights"):
        assert _tobytes(getattr(got, name)) == _tobytes(getattr(ref, name))
    assert shim.num_rows == X.shape[0]
    assert shim.feature_shards["g"].shape == X.shape


def test_assembly_bitwise_equals_one_shot_dense(rng):
    """Dense regime (density >= threshold): the device-side scatter of
    the exact CSR pieces must reproduce the host densify-then-upload
    array bit for bit."""
    n, d = 211, 12
    X = sp.csr_matrix(rng.normal(0, 1, (n, d)) *
                      (rng.random((n, d)) < 0.6))
    y = (rng.random(n) < 0.5).astype(float)
    ref = _one_shot_batch(X, y, None, None)
    shim = assemble_fixed_effect_batch(FakeStream(X, y, 50), "g")
    got = shim.fixed_effect_batch("g")
    assert type(got.features) is type(ref.features)  # DenseFeatures
    assert _tobytes(got.features.x) == _tobytes(ref.features.x)


def test_shim_rejects_wrong_shard_and_dtype(problem):
    X, y, off, w = problem
    shim = assemble_fixed_effect_batch(FakeStream(X, y, 64, off, w), "g")
    with pytest.raises(KeyError, match="assembled shard"):
        shim.fixed_effect_batch("other")
    with pytest.raises(ValueError, match="assembled as"):
        shim.fixed_effect_batch("g", dtype=jnp.float16)


def test_cache_padding_and_residency(problem):
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    assert cache.n_rows == X.shape[0]
    assert cache.n_shards == 6  # ceil(517/100)
    for e in cache.entries:
        assert e.rows_bucket >= e.n_rows
        assert e.rows_bucket & (e.rows_bucket - 1) == 0  # pow2
        assert e.nnz_bucket >= e.nnz
        assert e.feats is not None  # unbounded -> fully resident
        assert e.spill is None  # spill records freed
        # padded row columns carry weight 0 beyond the true rows
        wts = np.asarray(e.weights)
        assert (wts[e.n_rows:] == 0).all()
    # replay is pure hits
    list(cache.blocks())
    s = cache.stats()
    assert s["hits"] == cache.n_shards and s["misses"] == 0
    assert s["evictions"] == 0


def test_cache_spill_reupload_bitwise(problem):
    """Eviction + prefetched re-upload must reproduce the evicted arrays
    exactly — residency can never change a partial."""
    X, y, off, w = problem
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    block_bytes = max(e.feature_bytes for e in resident.entries)
    spill = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g",
        hbm_budget_bytes=2 * block_bytes)
    assert spill.stats()["resident_shards"] < spill.n_shards
    got = {b.index: b for b in spill.blocks()}
    for e_ref in resident.entries:
        b = got[e_ref.index]
        for name in ("values", "col_ids", "row_ids"):
            assert _tobytes(getattr(b.feats, name)) == \
                _tobytes(getattr(e_ref.feats, name))
    s = spill.stats()
    assert s["misses"] > 0 and s["evictions"] > 0
    assert s["bytes_reuploaded"] == s["misses"] * block_bytes \
        or s["bytes_reuploaded"] > 0
    # cache-accounted bytes stay at/below budget once the epoch settles
    assert spill.device_bytes <= max(2 * block_bytes,
                                     max(e.feature_bytes
                                         for e in spill.entries))


def test_cache_minimal_budget_keeps_only_in_hand_block(problem):
    """Budget below one block: exactly the in-hand block stays resident
    (you cannot accumulate a block that is not there)."""
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=1)
    for expect, b in enumerate(cache.blocks(prefetch_depth=0)):
        assert b.index == expect
        resident = [e.index for e in cache.entries if e.feats is not None]
        assert resident == [expect]


def test_cache_ingest_respects_budget(problem):
    """Evict-as-you-go: ingest-peak device bytes stay O(budget + one
    block), never O(dataset) — the --hbm-budget contract must hold
    DURING ingest, which is exactly when the dataset does not fit."""
    X, y, off, w = problem
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    block = max(e.feature_bytes for e in resident.entries)
    budget = 2 * block
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=budget)
    assert cache.stats()["evictions"] > 0  # evicted while ingesting
    assert cache.peak_device_bytes <= budget + block
    assert cache.device_bytes <= budget


def test_cache_replay_aware_eviction_beats_lru_thrash(problem):
    """Budget one block short of full residency, EQUAL block sizes (the
    policy's worst case): plain LRU would miss on EVERY access (the
    least-recently-used block is always the next one needed on a cyclic
    scan, n misses/epoch); the replay-aware policy amortizes to
    1 + 1/(n-1) misses/epoch (the in-hand block must stay cached, so
    the resident hole walks and pays one extra miss per wrap)."""
    X, y, off, w = problem
    X, y, off, w = X[:500], y[:500], off[:500], w[:500]  # 5 equal shards
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    n = resident.n_shards
    sizes = {e.feature_bytes for e in resident.entries}
    assert len(sizes) == 1  # equal blocks — the worst case for the bound
    per_block = sizes.pop()
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g",
        hbm_budget_bytes=(n - 1) * per_block)
    epochs = 2 * (n - 1)  # two full wrap cycles
    for _ in range(epochs):
        list(cache.blocks(prefetch_depth=0))
    s = cache.stats()
    bound = epochs + -(-epochs // (n - 1))  # 1/epoch + 1 extra per wrap
    assert s["misses"] <= bound, (s["misses"], bound)
    assert s["hits"] >= epochs * n - bound
    # LRU would have missed on every single access:
    assert s["misses"] < epochs * n / 2


def test_cache_snapshot_survives_eviction(problem):
    """A handed-out block must stay usable even after the cache evicts
    it (the snapshot holds its own reference)."""
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=1)
    blocks = list(cache.blocks(prefetch_depth=2))  # prefetch races evicts
    assert len(blocks) == cache.n_shards
    for b in blocks:
        assert b.feats is not None
        np.asarray(b.feats.values)  # still materializable


def test_cache_stats_keys(problem):
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(FakeStream(X, y, 200, off, w),
                                         "g", hbm_budget_bytes=10 << 20)
    s = cache.stats()
    for key in ("hits", "misses", "evictions", "bytes_reuploaded",
                "epochs", "shards", "rows", "bucket_shapes",
                "hbm_budget_bytes", "device_bytes", "peak_device_bytes",
                "resident_shards", "spill_dtype", "spill_source",
                "spill_bytes_host", "spill_bytes_written", "redecodes",
                "bytes_redecoded"):
        assert key in s, key
    assert s["spill_dtype"] == "f32" and s["spill_source"] == "buffer"


def test_empty_stream_raises():
    X = sp.csr_matrix((0, 4))
    with pytest.raises(ValueError, match="no rows"):
        assemble_fixed_effect_batch(FakeStream(X, np.zeros(0), 10), "g")
    with pytest.raises(ValueError, match="no rows"):
        DeviceShardCache.from_stream(FakeStream(X, np.zeros(0), 10), "g")


# -- spill codecs ----------------------------------------------------------


def _feat_bytes(feats):
    return tuple(_tobytes(getattr(feats, k))
                 for k in ("values", "col_ids", "row_ids"))


def _padded(X, rows_pad, nnz_pad):
    X = sp.csr_matrix(X)
    X.sort_indices()
    return X, padded_csr_arrays(X, rows_pad, nnz_pad)


def test_spill_codec_f32_roundtrip_is_bitwise(rng):
    """f32 spill is the PR-5 raw triplet: restore re-uploads the evicted
    bytes verbatim."""
    X, (vals, cols, rows) = _padded(
        sp.random(90, 50, density=0.1, random_state=0), 128, 1024)
    blk = encode_spill(vals, cols, rows, X.nnz, "f32")
    assert blk.dtype_tag == "f32" and blk.nbytes == 12 * 1024
    feats = restore_spilled_features(blk, 128, 50, None)
    assert _feat_bytes(feats) == (vals.tobytes(), cols.tobytes(),
                                  rows.tobytes())


@pytest.mark.parametrize("nnz_pad", ["exact", 2048])
def test_spill_codec_bf16_roundtrip_indices_bitwise(rng, nnz_pad):
    """bf16 spill: index streams round-trip BIT-exactly (delta codes are
    lossless), values round-trip through bfloat16 rounding — including
    at the bucket boundary (nnz == nnz_bucket, zero padding)."""
    import ml_dtypes

    X = sp.random(60, 300, density=0.05, random_state=1, format="csr")
    X.data[:] = rng.normal(0, 1, X.nnz)
    pad = X.nnz if nnz_pad == "exact" else nnz_pad
    X, (vals, cols, rows) = _padded(X, 64, pad)
    blk = encode_spill(vals, cols, rows, X.nnz, "bf16")
    assert blk.dtype_tag == "bf16"
    # u8 delta codes at this shape: 1 byte per index stream + 2-byte
    # values = 4/12 of the f32 spill record.
    assert blk.enc_cols.dtype == np.uint8
    assert blk.enc_rows.dtype == np.uint8
    assert blk.nbytes * 3 == 12 * pad
    feats = restore_spilled_features(blk, 64, 300, None)
    got_v, got_c, got_r = _feat_bytes(feats)
    assert got_c == cols.tobytes()
    assert got_r == rows.tobytes()
    want = vals.astype(ml_dtypes.bfloat16).astype(np.float32)
    assert got_v == want.tobytes()


def test_spill_codec_bf16_empty_and_single_entry():
    """Degenerate blocks: zero nnz (all-empty rows) and one entry."""
    blk = encode_spill(np.zeros(16, np.float32), np.zeros(16, np.int32),
                       np.zeros(16, np.int32), 0, "bf16")
    feats = restore_spilled_features(blk, 8, 10, None)
    assert not np.asarray(feats.values).any()
    assert not np.asarray(feats.col_ids).any()
    one = sp.csr_matrix((np.asarray([2.5]), (np.asarray([3]),
                                             np.asarray([7]))),
                        shape=(5, 10))
    _, (vals, cols, rows) = _padded(one, 8, 16)
    blk = encode_spill(vals, cols, rows, 1, "bf16")
    feats = restore_spilled_features(blk, 8, 10, None)
    assert _feat_bytes(feats)[1:] == (cols.tobytes(), rows.tobytes())
    assert np.asarray(feats.values)[0] == np.float32(2.5)  # exact in bf16


def test_spill_codec_u16_and_i32_overflow_fallback(rng):
    """Code-width selection: deltas in (255, 65535] pick u16; a delta
    beyond u16 (huge column jump) falls back to RAW i32 ids — and every
    width round-trips the index bits exactly."""
    # within-row jumps of ~10_000 -> u16 codes
    mid = sp.csr_matrix((np.ones(4), ([0, 0, 1, 1], [5, 10_005, 3, 9_003])),
                        shape=(2, 20_000))
    _, (vals, cols, rows) = _padded(mid, 2, 8)
    blk = encode_spill(vals, cols, rows, 4, "bf16")
    assert blk.enc_cols.dtype == np.uint16
    feats = restore_spilled_features(blk, 2, 20_000, None)
    assert _feat_bytes(feats)[1] == cols.tobytes()
    # a 200_000-column jump overflows u16 -> raw i32 fallback
    big = sp.csr_matrix((np.ones(2), ([0, 0], [1, 200_001])),
                        shape=(1, 300_000))
    _, (vals, cols, rows) = _padded(big, 2, 8)
    blk = encode_spill(vals, cols, rows, 2, "bf16")
    assert blk.enc_cols.dtype == np.int32
    assert blk.enc_rows.dtype == np.uint8  # streams fall back per-stream
    feats = restore_spilled_features(blk, 2, 300_000, None)
    assert _feat_bytes(feats)[1:] == (cols.tobytes(), rows.tobytes())


def test_spill_codec_rejects_unknown_dtype(rng):
    with pytest.raises(ValueError, match="spill_dtype"):
        encode_spill(np.zeros(4, np.float32), np.zeros(4, np.int32),
                     np.zeros(4, np.int32), 0, "f16")


# -- compressed spill + redecode tiers through the cache -------------------


def _block_map(cache, **kw):
    return {b.index: _feat_bytes(b.feats) for b in cache.blocks(**kw)}


def test_cache_bf16_spill_indices_bitwise_and_host_bytes_third(problem):
    """bf16 buffer spill: EVERY block's index bits equal the resident
    cache's exactly; values equal the bf16 round-trip for resident and
    restored blocks alike (quantized once at ingest, so replays are
    residency-independent); host spill bytes measure 1/3 of the f32
    spill record (u8 index codes at this shape)."""
    import ml_dtypes

    X, y, off, w = problem
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    block_bytes = max(e.feature_bytes for e in resident.entries)
    ref = {e.index: _feat_bytes(e.feats) for e in resident.entries}
    f32 = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g",
        hbm_budget_bytes=2 * block_bytes)
    bf16 = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g",
        hbm_budget_bytes=2 * block_bytes, spill_dtype="bf16")
    assert bf16.spill_bytes_host * 3 == f32.spill_bytes_host
    assert bf16.stats()["spill_bytes_written"] == bf16.spill_bytes_host
    got = _block_map(bf16)
    for idx, (rv, rc, rr) in ref.items():
        gv, gc, gr = got[idx]
        assert (gc, gr) == (rc, rr), idx
        want = np.frombuffer(rv, np.float32).astype(
            ml_dtypes.bfloat16).astype(np.float32)
        assert gv == want.tobytes(), idx
    # two full replay epochs produce identical bits (restore from the
    # same spill records is deterministic)
    assert _block_map(bf16) == got
    # re-upload traffic is the COMPACT bytes: exactly 1/3 of the f32
    # tier's over the identical two-epoch access pattern
    list(f32.blocks())
    list(f32.blocks())
    s_f32, s_bf16 = f32.stats(), bf16.stats()
    assert s_bf16["misses"] == s_f32["misses"] > 0
    assert s_bf16["bytes_reuploaded"] * 3 == s_f32["bytes_reuploaded"]


def test_cache_redecode_tier_drops_host_copy_and_replays_bitwise(problem):
    """spill_source='redecode': NO host spill bytes; misses re-fetch the
    block's source rows and the replay is bit-for-bit the resident
    cache across multiple epochs."""
    X, y, off, w = problem
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    block_bytes = max(e.feature_bytes for e in resident.entries)
    ref = {e.index: _feat_bytes(e.feats) for e in resident.entries}

    def fetch(row_start, n_rows):
        s = slice(row_start, row_start + n_rows)
        return GameDataset.build(responses=y[s], feature_shards={"g": X[s]},
                                 offsets=off[s], weights=w[s])

    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g",
        hbm_budget_bytes=2 * block_bytes,
        spill_source="redecode", redecode_fetch=fetch)
    assert cache.spill_bytes_host == 0
    assert all(e.spill is None for e in cache.entries)
    for _ in range(2):
        assert _block_map(cache) == ref
    s = cache.stats()
    assert s["redecodes"] == s["misses"] > 0
    assert s["bytes_redecoded"] > 0
    assert s["spill_source"] == "redecode"


def test_cache_redecode_validates_fetch_and_requires_hook(problem):
    """Constructor contract: redecode + budget needs the fetch hook; a
    fetch that returns the wrong rows (input changed under the cache)
    fails loudly."""
    X, y, off, w = problem
    with pytest.raises(ValueError, match="redecode_fetch"):
        DeviceShardCache.from_stream(
            FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=1,
            spill_source="redecode")

    def bad_fetch(row_start, n_rows):
        return GameDataset.build(responses=y[:n_rows],
                                 feature_shards={"g": X[:n_rows] * 2.0})

    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=1,
        spill_source="redecode", redecode_fetch=bad_fetch)
    with pytest.raises(RuntimeError, match="changed under the cache"):
        list(cache.blocks(prefetch_depth=0))


def test_cache_rejects_unknown_spill_options(problem):
    X, y, off, w = problem
    with pytest.raises(ValueError, match="spill_dtype"):
        DeviceShardCache.from_stream(FakeStream(X, y, 100, off, w), "g",
                                     spill_dtype="f64")
    with pytest.raises(ValueError, match="spill_source"):
        DeviceShardCache.from_stream(FakeStream(X, y, 100, off, w), "g",
                                     spill_source="disk")
    # bf16 + redecode would silently train as f32 while reporting bf16
    # (redecode keeps no buffers to compress) — mutually exclusive.
    with pytest.raises(ValueError, match="pick one"):
        DeviceShardCache.from_stream(FakeStream(X, y, 100, off, w), "g",
                                     hbm_budget_bytes=1,
                                     spill_dtype="bf16",
                                     spill_source="redecode",
                                     redecode_fetch=lambda s, n: None)


def test_cache_spill_bytes_host_accounting(problem):
    """The satellite gauge's source of truth: unbounded caches retain no
    host spill bytes; f32 buffer spill retains 12 bytes/padded-nnz per
    shard; the registry twin mirrors it."""
    from photon_ml_tpu import telemetry

    X, y, off, w = problem
    unbounded = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    assert unbounded.spill_bytes_host == 0
    assert unbounded.stats()["spill_bytes_host"] == 0
    telemetry.reset()
    telemetry.enable()
    try:
        cache = DeviceShardCache.from_stream(
            FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=1)
        want = sum(12 * e.nnz_bucket for e in cache.entries)
        assert cache.spill_bytes_host == want
        assert cache.stats()["spill_bytes_host"] == want
        snap = telemetry.snapshot()
        assert snap["gauges"]["data.shard_cache.spill_bytes_host"] == want
        assert snap["counters"][
            "data.shard_cache.spill_bytes_written"] == want
    finally:
        telemetry.disable()
        telemetry.reset()
