"""data/shard_cache.py: streamed ingest -> exact assembly / padded device
cache with LRU spill. The assembly contract (bitwise equality with the
one-shot `fixed_effect_batch`) is what makes `--stream-train` write a
byte-identical model to the one-shot driver.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.shard_cache import (
    DeviceShardCache,
    assemble_fixed_effect_batch,
)


class FakeStream:
    """GameDataset batches cut from one host matrix — the BlockGameStream
    shape without Avro (decode identity is test_block_stream's job)."""

    def __init__(self, X, y, batch_rows, offsets=None, weights=None):
        self.X = sp.csr_matrix(X)
        self.y = np.asarray(y, float)
        self.offsets = offsets
        self.weights = weights
        self.batch_rows = batch_rows

    def __iter__(self):
        n = self.X.shape[0]
        for s in range(0, n, self.batch_rows):
            e = min(n, s + self.batch_rows)
            yield GameDataset.build(
                responses=self.y[s:e], feature_shards={"g": self.X[s:e]},
                offsets=None if self.offsets is None else self.offsets[s:e],
                weights=None if self.weights is None else self.weights[s:e])

    def stats(self):
        return {"decode_path": "fake", "batches": -1}


@pytest.fixture
def problem(rng):
    n, d = 517, 37
    X = sp.random(n, d, density=0.08, random_state=5, format="csr")
    X.data[:] = rng.normal(0, 1, X.nnz)
    y = (rng.random(n) < 0.5).astype(float)
    off = rng.normal(0, 0.2, n)
    w = rng.gamma(1.0, 1.0, n)
    return X, y, off, w


def _one_shot_batch(X, y, off, w, dtype=jnp.float32):
    data = GameDataset.build(responses=y, feature_shards={"g": X},
                             offsets=off, weights=w)
    return data.fixed_effect_batch("g", dtype=dtype)


def _tobytes(a):
    return np.asarray(a).tobytes()


@pytest.mark.parametrize("batch_rows", [64, 33, 517, 1000])
def test_assembly_bitwise_equals_one_shot_sparse(problem, batch_rows):
    """CSR regime (density < threshold): values/col_ids/row_ids and the
    row columns must be the one-shot arrays bit for bit, for aligned,
    non-aligned, exact and oversized batch_rows."""
    X, y, off, w = problem
    ref = _one_shot_batch(X, y, off, w)
    shim = assemble_fixed_effect_batch(
        FakeStream(X, y, batch_rows, off, w), "g")
    got = shim.fixed_effect_batch("g")
    assert type(got.features) is type(ref.features)
    for name in ("values", "col_ids", "row_ids"):
        assert _tobytes(getattr(got.features, name)) == \
            _tobytes(getattr(ref.features, name)), name
    for name in ("labels", "offsets", "weights"):
        assert _tobytes(getattr(got, name)) == _tobytes(getattr(ref, name))
    assert shim.num_rows == X.shape[0]
    assert shim.feature_shards["g"].shape == X.shape


def test_assembly_bitwise_equals_one_shot_dense(rng):
    """Dense regime (density >= threshold): the device-side scatter of
    the exact CSR pieces must reproduce the host densify-then-upload
    array bit for bit."""
    n, d = 211, 12
    X = sp.csr_matrix(rng.normal(0, 1, (n, d)) *
                      (rng.random((n, d)) < 0.6))
    y = (rng.random(n) < 0.5).astype(float)
    ref = _one_shot_batch(X, y, None, None)
    shim = assemble_fixed_effect_batch(FakeStream(X, y, 50), "g")
    got = shim.fixed_effect_batch("g")
    assert type(got.features) is type(ref.features)  # DenseFeatures
    assert _tobytes(got.features.x) == _tobytes(ref.features.x)


def test_shim_rejects_wrong_shard_and_dtype(problem):
    X, y, off, w = problem
    shim = assemble_fixed_effect_batch(FakeStream(X, y, 64, off, w), "g")
    with pytest.raises(KeyError, match="assembled shard"):
        shim.fixed_effect_batch("other")
    with pytest.raises(ValueError, match="assembled as"):
        shim.fixed_effect_batch("g", dtype=jnp.float16)


def test_cache_padding_and_residency(problem):
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    assert cache.n_rows == X.shape[0]
    assert cache.n_shards == 6  # ceil(517/100)
    for e in cache.entries:
        assert e.rows_bucket >= e.n_rows
        assert e.rows_bucket & (e.rows_bucket - 1) == 0  # pow2
        assert e.nnz_bucket >= e.nnz
        assert e.feats is not None  # unbounded -> fully resident
        assert e.host_values is None  # spill buffers freed
        # padded row columns carry weight 0 beyond the true rows
        wts = np.asarray(e.weights)
        assert (wts[e.n_rows:] == 0).all()
    # replay is pure hits
    list(cache.blocks())
    s = cache.stats()
    assert s["hits"] == cache.n_shards and s["misses"] == 0
    assert s["evictions"] == 0


def test_cache_spill_reupload_bitwise(problem):
    """Eviction + prefetched re-upload must reproduce the evicted arrays
    exactly — residency can never change a partial."""
    X, y, off, w = problem
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    block_bytes = max(e.feature_bytes for e in resident.entries)
    spill = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g",
        hbm_budget_bytes=2 * block_bytes)
    assert spill.stats()["resident_shards"] < spill.n_shards
    got = {b.index: b for b in spill.blocks()}
    for e_ref in resident.entries:
        b = got[e_ref.index]
        for name in ("values", "col_ids", "row_ids"):
            assert _tobytes(getattr(b.feats, name)) == \
                _tobytes(getattr(e_ref.feats, name))
    s = spill.stats()
    assert s["misses"] > 0 and s["evictions"] > 0
    assert s["bytes_reuploaded"] == s["misses"] * block_bytes \
        or s["bytes_reuploaded"] > 0
    # cache-accounted bytes stay at/below budget once the epoch settles
    assert spill.device_bytes <= max(2 * block_bytes,
                                     max(e.feature_bytes
                                         for e in spill.entries))


def test_cache_minimal_budget_keeps_only_in_hand_block(problem):
    """Budget below one block: exactly the in-hand block stays resident
    (you cannot accumulate a block that is not there)."""
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=1)
    for expect, b in enumerate(cache.blocks(prefetch_depth=0)):
        assert b.index == expect
        resident = [e.index for e in cache.entries if e.feats is not None]
        assert resident == [expect]


def test_cache_ingest_respects_budget(problem):
    """Evict-as-you-go: ingest-peak device bytes stay O(budget + one
    block), never O(dataset) — the --hbm-budget contract must hold
    DURING ingest, which is exactly when the dataset does not fit."""
    X, y, off, w = problem
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    block = max(e.feature_bytes for e in resident.entries)
    budget = 2 * block
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=budget)
    assert cache.stats()["evictions"] > 0  # evicted while ingesting
    assert cache.peak_device_bytes <= budget + block
    assert cache.device_bytes <= budget


def test_cache_replay_aware_eviction_beats_lru_thrash(problem):
    """Budget one block short of full residency, EQUAL block sizes (the
    policy's worst case): plain LRU would miss on EVERY access (the
    least-recently-used block is always the next one needed on a cyclic
    scan, n misses/epoch); the replay-aware policy amortizes to
    1 + 1/(n-1) misses/epoch (the in-hand block must stay cached, so
    the resident hole walks and pays one extra miss per wrap)."""
    X, y, off, w = problem
    X, y, off, w = X[:500], y[:500], off[:500], w[:500]  # 5 equal shards
    resident = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g")
    n = resident.n_shards
    sizes = {e.feature_bytes for e in resident.entries}
    assert len(sizes) == 1  # equal blocks — the worst case for the bound
    per_block = sizes.pop()
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g",
        hbm_budget_bytes=(n - 1) * per_block)
    epochs = 2 * (n - 1)  # two full wrap cycles
    for _ in range(epochs):
        list(cache.blocks(prefetch_depth=0))
    s = cache.stats()
    bound = epochs + -(-epochs // (n - 1))  # 1/epoch + 1 extra per wrap
    assert s["misses"] <= bound, (s["misses"], bound)
    assert s["hits"] >= epochs * n - bound
    # LRU would have missed on every single access:
    assert s["misses"] < epochs * n / 2


def test_cache_snapshot_survives_eviction(problem):
    """A handed-out block must stay usable even after the cache evicts
    it (the snapshot holds its own reference)."""
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 100, off, w), "g", hbm_budget_bytes=1)
    blocks = list(cache.blocks(prefetch_depth=2))  # prefetch races evicts
    assert len(blocks) == cache.n_shards
    for b in blocks:
        assert b.feats is not None
        np.asarray(b.feats.values)  # still materializable


def test_cache_stats_keys(problem):
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(FakeStream(X, y, 200, off, w),
                                         "g", hbm_budget_bytes=10 << 20)
    s = cache.stats()
    for key in ("hits", "misses", "evictions", "bytes_reuploaded",
                "epochs", "shards", "rows", "bucket_shapes",
                "hbm_budget_bytes", "device_bytes", "peak_device_bytes",
                "resident_shards"):
        assert key in s, key


def test_empty_stream_raises():
    X = sp.csr_matrix((0, 4))
    with pytest.raises(ValueError, match="no rows"):
        assemble_fixed_effect_batch(FakeStream(X, np.zeros(0), 10), "g")
    with pytest.raises(ValueError, match="no rows"):
        DeviceShardCache.from_stream(FakeStream(X, np.zeros(0), 10), "g")
