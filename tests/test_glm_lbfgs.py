"""Margin-cached GLM L-BFGS (optimization/glm_lbfgs.py): equivalence with
the generic solver and with autodiff, across losses, layouts, normalization,
and vmap batching."""

import numpy as np

from tests.conftest import gold
import jax
import jax.numpy as jnp
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.normalization import build_normalization_context
from photon_ml_tpu.data.stats import BasicStatisticalSummary
from photon_ml_tpu.ops import DenseFeatures, GLMObjective
from photon_ml_tpu.ops.features import csr_from_scipy
from photon_ml_tpu.ops.glm_objective import make_batch
from photon_ml_tpu.ops.losses import (
    LogisticLoss,
    PoissonLoss,
    SquaredLoss,
    loss_for_task,
)
from photon_ml_tpu.optimization import minimize_lbfgs
from photon_ml_tpu.optimization.glm_lbfgs import minimize_lbfgs_glm


def _problem(rng, n=200, d=7, poisson=False):
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d) * 0.5
    if poisson:
        y = rng.poisson(np.exp(np.clip(x @ w, -5, 3))).astype(float)
    else:
        y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    return x, y


@pytest.mark.parametrize("loss", [LogisticLoss, SquaredLoss, PoissonLoss],
                         ids=["logistic", "squared", "poisson"])
def test_gradient_from_margins_matches_autodiff(rng, loss):
    x, y = _problem(rng, poisson=(loss is PoissonLoss))
    obj = GLMObjective(loss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
                       offsets=jnp.asarray(rng.normal(size=len(y)) * 0.1),
                       weights=jnp.asarray(rng.random(len(y)) + 0.5))
    w = jnp.asarray(rng.normal(size=7))
    l2 = 0.7
    z = obj.margins(w, batch)
    g_fast = obj.gradient_from_margins(w, z, batch, l2)
    g_ad = jax.grad(obj.value)(w, batch, l2)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_ad),
                               atol=gold(1e-10))
    v_fast = obj.value_from_margins(z, jnp.vdot(w, w), batch, l2)
    np.testing.assert_allclose(float(v_fast), float(obj.value(w, batch, l2)),
                               rtol=gold(1e-12))


def test_gradient_from_margins_with_normalization(rng):
    x, y = _problem(rng)
    stats = BasicStatisticalSummary.compute(x)
    norm = build_normalization_context("STANDARDIZATION", stats,
                                       intercept_id=6)
    obj = GLMObjective(LogisticLoss, norm)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    w = jnp.asarray(rng.normal(size=7))
    z = obj.margins(w, batch)
    g_fast = obj.gradient_from_margins(w, z, batch, 0.3)
    g_ad = jax.grad(obj.value)(w, batch, 0.3)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_ad),
                               atol=gold(1e-10))
    # margin_direction is the linear part: margins(w + p) - margins(w).
    p = jnp.asarray(rng.normal(size=7))
    np.testing.assert_allclose(
        np.asarray(obj.margins(w + p, batch) - z),
        np.asarray(obj.margin_direction(p, batch)), atol=gold(1e-10))


@pytest.mark.parametrize("layout", ["dense", "csr"])
def test_fast_path_matches_generic_lbfgs(rng, layout):
    x, y = _problem(rng, n=400, d=9)
    obj = GLMObjective(LogisticLoss)
    if layout == "dense":
        feats = DenseFeatures(jnp.asarray(x))
    else:
        feats = csr_from_scipy(sp.csr_matrix(x), dtype=jnp.float64)
    batch = make_batch(feats, jnp.asarray(y))
    l2 = 0.5
    fast = minimize_lbfgs_glm(obj, batch, jnp.zeros(9), l2, tol=1e-10)
    generic = minimize_lbfgs(obj.value, jnp.zeros(9),
                             args=(batch, jnp.asarray(l2)), tol=1e-10)
    np.testing.assert_allclose(float(fast.value), float(generic.value),
                               rtol=gold(1e-9))
    np.testing.assert_allclose(np.asarray(fast.x), np.asarray(generic.x),
                               atol=gold(1e-6, f32_floor=2e-3))


def test_fast_path_vmap_batched(rng):
    """The random-effect mode: vmapped solves match per-entity solves."""
    E, n, d = 4, 50, 5
    xs = rng.normal(size=(E, n, d))
    ys = (rng.random((E, n)) < 0.5).astype(float)
    obj = GLMObjective(LogisticLoss)

    def fit(x, y):
        batch = make_batch(DenseFeatures(x), y)
        return minimize_lbfgs_glm(obj, batch, jnp.zeros(d, x.dtype), 0.5,
                                  tol=1e-10)

    batched = jax.vmap(fit)(jnp.asarray(xs), jnp.asarray(ys))
    for e in range(E):
        single = fit(jnp.asarray(xs[e]), jnp.asarray(ys[e]))
        np.testing.assert_allclose(np.asarray(batched.x[e]),
                                   np.asarray(single.x),
                                   atol=gold(1e-7, f32_floor=2e-3))


def test_solve_glm_uses_fast_path_unbounded(rng):
    """solve_glm routes unconstrained L2 LBFGS to the margin-cached solver;
    result must agree with the generic one it replaced."""
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.optimization.solver import solve_glm
    from photon_ml_tpu.types import TaskType

    x, y = _problem(rng)
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    batch = make_batch(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    cfg = GLMOptimizationConfiguration(
        max_iterations=100, tolerance=1e-10, regularization_weight=2.0,
        regularization_context=RegularizationContext(RegularizationType.L2))
    res = solve_glm(obj, batch, cfg, jnp.zeros(7))
    generic = minimize_lbfgs(obj.value, jnp.zeros(7),
                             args=(batch, jnp.asarray(2.0)), tol=1e-10)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(generic.x),
                               atol=1e-6)


def test_fast_path_coef_history(rng):
    x, y = _problem(rng)
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    res = minimize_lbfgs_glm(obj, batch, jnp.zeros(7), 0.5, tol=1e-10,
                             track_coefficients=True)
    hist = np.asarray(res.coef_history)
    iters = int(res.iterations)
    np.testing.assert_allclose(hist[iters], np.asarray(res.x), atol=0)
    assert np.all(np.isnan(hist[iters + 1:]))
