"""Live observability plane (photon_ml_tpu/telemetry/{exposition,
recorder,slo}.py): Prometheus text rendering verified through a minimal
parser of the exposition format, the stdlib HTTP server's routes, the
flight recorder's ring/dump semantics, and SLO burn-rate math."""

import json
import os
import re
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import (
    FlightRecorder,
    LatencyObjective,
    ObservabilityServer,
    RatioObjective,
    SLOTracker,
    install_sigterm_dump,
    parse_slo,
    prometheus_name,
    render_prometheus,
)
from photon_ml_tpu.telemetry.registry import MetricsRegistry

# -- minimal Prometheus text-format parser ---------------------------------
# The acceptance contract: /metrics must parse under OUR OWN strict
# reader of text format 0.0.4 — HELP/TYPE preambles, sample syntax,
# histogram bucket monotonicity and the le="+Inf" == _count identity.

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})? '
    r'(?P<value>[^ ]+)'
    # Optional OpenMetrics exemplar suffix (PR 11): only histogram
    # bucket lines may carry one; labels + value + unix timestamp.
    r'(?: # \{(?P<exlabels>[^}]*)\} (?P<exvalue>[^ ]+) (?P<exts>[^ ]+))?$')


def _parse_labels(raw: str) -> dict:
    labels = {}
    for pair in raw.split(","):
        k, _, v = pair.partition("=")
        assert v.startswith('"') and v.endswith('"'), raw
        labels[k] = v[1:-1]
    return labels


def parse_prometheus(text: str):
    """text exposition -> {family: {"type": t, "help": h, "samples":
    [(sample_name, labels_dict, float_value)], "exemplars":
    [(sample_name, labels_dict, exemplar_dict)]}}; raises
    AssertionError on any malformed line (this parser IS the test
    oracle). Exemplars are validated structurally: bucket samples only,
    labels parse, value and timestamp are floats."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families = {}
    current = None
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            current = families[name] = {"type": None, "help": help_text,
                                        "samples": [], "exemplars": []}
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            assert name in families, f"TYPE before HELP for {name}"
            assert mtype in ("counter", "gauge", "histogram", "summary")
            families[name]["type"] = mtype
        elif line.startswith("#"):
            continue  # comment (collision reports land here)
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed sample line: {line!r}"
            labels = (_parse_labels(m.group("labels"))
                      if m.group("labels") else {})
            value = float(m.group("value"))
            sample = m.group("name")
            exemplar = None
            if m.group("exlabels") is not None:
                assert sample.endswith("_bucket"), \
                    f"exemplar on a non-bucket sample: {line!r}"
                exemplar = {
                    "labels": _parse_labels(m.group("exlabels")),
                    "value": float(m.group("exvalue")),
                    "ts": float(m.group("exts")),
                }
            # samples attach to their family (histogram series carry
            # _bucket/_sum/_count suffixes)
            fam = None
            for cand in (sample, sample.rsplit("_", 1)[0]):
                if cand in families:
                    fam = cand
                    break
            if fam is None and sample.endswith("_bucket"):
                fam = sample[:-len("_bucket")]
            assert fam in families, f"sample {sample!r} without HELP/TYPE"
            families[fam]["samples"].append((sample, labels, value))
            if exemplar is not None:
                assert families[fam]["type"] in (None, "histogram"), \
                    f"exemplar on non-histogram family: {line!r}"
                families[fam]["exemplars"].append(
                    (sample, labels, exemplar))
    for name, fam in families.items():
        if fam["type"] == "histogram":
            buckets = [(float(la["le"]) if la["le"] != "+Inf"
                        else float("inf"), v)
                       for s, la, v in fam["samples"]
                       if s == name + "_bucket"]
            assert buckets, f"histogram {name} has no buckets"
            bounds = [b for b, _ in buckets]
            counts = [c for _, c in buckets]
            assert bounds == sorted(bounds)
            assert bounds[-1] == float("inf"), "missing +Inf bucket"
            assert counts == sorted(counts), \
                f"{name} cumulative bucket counts must be monotone"
            count = [v for s, _, v in fam["samples"]
                     if s == name + "_count"]
            assert count and count[0] == counts[-1], \
                f"{name}: le=+Inf bucket must equal _count"
    return families


@pytest.fixture
def enabled_registry():
    """Fresh private registry + telemetry enabled (the process registry
    stays untouched except for the enable flag)."""
    telemetry.enable()
    try:
        yield MetricsRegistry()
    finally:
        telemetry.disable()


# -- rendering edge cases --------------------------------------------------

def test_empty_registry_renders_and_parses(enabled_registry):
    text = render_prometheus(enabled_registry)
    assert parse_prometheus(text) == {}


def test_counter_gauge_histogram_families(enabled_registry):
    reg = enabled_registry
    reg.counter("serving.frontend.admitted").inc(5)
    reg.gauge("data.shard_cache.device_bytes").set(123.5)
    h = reg.histogram("serving.request_latency_seconds",
                      buckets=[0.1, 1.0, 10.0])
    h.observe(0.1)    # le semantics: lands in the bucket 0.1 CLOSES
    h.observe(0.5)
    h.observe(100.0)  # overflow -> +Inf only
    fams = parse_prometheus(render_prometheus(reg))
    c = fams["serving_frontend_admitted_total"]
    assert c["type"] == "counter"
    assert c["samples"] == [("serving_frontend_admitted_total", {}, 5.0)]
    # original dotted name rides in HELP
    assert "serving.frontend.admitted" in c["help"]
    g = fams["data_shard_cache_device_bytes"]
    assert g["type"] == "gauge"
    assert g["samples"][0][2] == 123.5
    hist = fams["serving_request_latency_seconds"]
    assert hist["type"] == "histogram"
    by_le = {la["le"]: v for s, la, v in hist["samples"]
             if s.endswith("_bucket")}
    assert by_le == {"0.1": 1.0, "1": 2.0, "10": 2.0, "+Inf": 3.0}
    scalars = {s: v for s, la, v in hist["samples"] if not la}
    assert scalars["serving_request_latency_seconds_count"] == 3.0
    assert scalars["serving_request_latency_seconds_sum"] == \
        pytest.approx(100.6)


def test_zero_observation_histogram(enabled_registry):
    reg = enabled_registry
    reg.histogram("training.iteration_seconds", buckets=[0.5, 5.0])
    fams = parse_prometheus(render_prometheus(reg))
    hist = fams["training_iteration_seconds"]
    values = [v for _, _, v in hist["samples"]]
    assert values == [0.0, 0.0, 0.0, 0.0, 0.0]  # 3 buckets + sum + count


def test_name_escaping(enabled_registry):
    assert prometheus_name("serving.frontend.admitted") == \
        "serving_frontend_admitted"
    assert prometheus_name("weird-name!x") == "weird_name_x"
    assert prometheus_name("0starts.with.digit") == "_0starts_with_digit"
    reg = enabled_registry
    reg.counter("weird-name!x").inc()
    fams = parse_prometheus(render_prometheus(reg))
    assert fams["weird_name_x_total"]["samples"][0][2] == 1.0
    # the original spelling is recoverable from HELP
    assert "weird-name!x" in fams["weird_name_x_total"]["help"]


def test_sanitization_collision_keeps_first_and_comments(enabled_registry):
    reg = enabled_registry
    reg.gauge("a.b").set(1)
    reg.gauge("a_b").set(2)
    text = render_prometheus(reg)
    fams = parse_prometheus(text)  # still VALID exposition
    assert len(fams["a_b"]["samples"]) == 1
    assert "# collision:" in text


def test_scrape_under_concurrent_mutation(enabled_registry):
    """A scrape racing observe/inc must stay internally consistent:
    every render parses, histogram cumulative counts stay monotone with
    le=+Inf == _count (enforced by the parser), and counters never go
    backwards across scrapes."""
    reg = enabled_registry
    c = reg.counter("stress.ops")
    h = reg.histogram("stress.latency_seconds", buckets=[1e-4, 1e-3, 1e-2])
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            c.inc()
            h.observe((i % 13) * 1e-4)
            i += 1

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        last_count = -1.0
        for _ in range(50):
            fams = parse_prometheus(render_prometheus(reg))
            total = fams["stress_ops_total"]["samples"][0][2]
            assert total >= last_count
            last_count = total
    finally:
        stop.set()
        for t in threads:
            t.join()


# -- observability server --------------------------------------------------

def _get(port, route, timeout=5):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{route}", timeout=timeout)


def test_server_routes(tmp_path):
    telemetry.reset()
    telemetry.enable()
    rec = FlightRecorder(max_events=64).install()
    tracker = SLOTracker(
        ["p99:serving.frontend.request_latency_seconds<=50ms"])
    dump_path = tmp_path / "flight.json"
    try:
        telemetry.counter("serving.frontend.admitted").inc(2)
        with telemetry.span("solve"):
            pass
        srv = ObservabilityServer(
            port=0, recorder=rec, slo_tracker=tracker,
            status_providers={"demo": lambda: {"x": 1},
                              "broken": lambda: 1 / 0},
            dump_path=dump_path)
        with srv:
            port = srv.port
            # /metrics: valid Prometheus text under our own parser,
            # carrying the registry counter
            resp = _get(port, "/metrics")
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            fams = parse_prometheus(resp.read().decode())
            assert fams["serving_frontend_admitted_total"][
                "samples"][0][2] == 2.0
            # /healthz
            hz = json.loads(_get(port, "/healthz").read())
            assert hz["status"] == "ok" and hz["uptime_seconds"] >= 0
            # /statusz: registry + stage attribution + providers + slo
            sz = json.loads(_get(port, "/statusz").read())
            assert sz["telemetry_enabled"] is True
            assert sz["metrics"]["counters"][
                "serving.frontend.admitted"] == 2
            assert "solve" in sz["stage_attribution"]
            assert sz["status"]["demo"] == {"x": 1}
            assert "ZeroDivisionError" in sz["status"]["broken"]["error"]
            # Broken providers are isolated AND visible: the failing
            # name surfaces in the payload and the obs.provider_errors
            # counter moves (PR 11 satellite — previously silent).
            assert sz["status"]["broken"]["provider"] == "broken"
            assert sz["failing_providers"] == ["broken"]
            assert sz["provider_errors"] == {"broken": 1}
            assert telemetry.counter("obs.provider_errors").value == 1
            sz2 = json.loads(_get(port, "/statusz").read())
            assert sz2["provider_errors"] == {"broken": 2}
            # /tracez serves the tail sampler (empty here; semantics in
            # tests/test_tracectx.py)
            tz = json.loads(_get(port, "/tracez").read())
            assert tz["seen"] == 0 and "traces" in tz
            assert "p99_serving_frontend_request_latency_seconds" \
                in sz["slo"]
            assert sz["flight_recorder"]["events_in_ring"] >= 1
            # /debugz/dump returns the dump AND writes dump_path
            dz = json.loads(_get(port, "/debugz/dump").read())
            assert any(e.get("name") == "solve"
                       for e in dz["traceEvents"])
            assert dump_path.exists()
            # unknown route -> 404 with the route list
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(port, "/nope")
            assert ei.value.code == 404
            assert "/metrics" in json.loads(ei.value.read())["routes"]
            assert srv.scrapes == 1  # only /metrics counts as a scrape
        # port survives stop() for metrics.json reporting
        assert srv.port == port
        assert srv.summary()["scrapes"] == 1
    finally:
        rec.uninstall()
        telemetry.disable()
        telemetry.reset()


def test_server_heartbeat_refreshes_gauges_and_deltas():
    telemetry.reset()
    telemetry.enable()
    rec = FlightRecorder(max_events=64, snapshot_interval_s=0.0)
    try:
        c = telemetry.counter("hb.work")
        srv = ObservabilityServer(port=0, recorder=rec, heartbeat_s=0.02)
        with srv:
            c.inc(5)  # no spans close: only the heartbeat can capture
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if telemetry.gauge(
                        "process.heartbeat_unix_time").value > 0 and any(
                        e[0] == "metrics" for e in list(rec._ring)):
                    break
                time.sleep(0.01)
        assert telemetry.gauge("process.uptime_seconds").value >= 0
        assert telemetry.gauge("process.heartbeat_unix_time").value > 0
        deltas = [e for e in list(rec._ring) if e[0] == "metrics"]
        assert deltas and any("hb.work" in e[2] for e in deltas)
    finally:
        telemetry.disable()
        telemetry.reset()


# -- flight recorder -------------------------------------------------------

def test_recorder_ring_bounds_and_dump(tmp_path):
    telemetry.reset()
    telemetry.enable()
    rec = FlightRecorder(max_events=8, snapshot_interval_s=1e9).install()
    try:
        for i in range(20):
            with telemetry.span(f"stage_{i}"):
                pass
        st = rec.stats()
        assert st["events_in_ring"] == 8
        assert st["events_seen"] == 20 and st["events_evicted"] == 12
        path = tmp_path / "flight.json"
        dump = rec.dump(path, reason="test")
        names = [e["name"] for e in dump["traceEvents"]
                 if e.get("ph") == "X"]
        # the ring keeps the MOST RECENT events — the fault-time window
        assert names == [f"stage_{i}" for i in range(12, 20)]
        assert dump["flight"]["reason"] == "test"
        assert dump["flight"]["events_evicted"] == 12
        on_disk = json.loads(path.read_text())
        assert on_disk["traceEvents"]  # Perfetto-loadable JSON
        assert {e["ph"] for e in on_disk["traceEvents"]} <= {"M", "X", "C"}
        rec.clear()
        assert rec.stats()["events_in_ring"] == 0
    finally:
        rec.uninstall()
        telemetry.disable()
        telemetry.reset()


def test_recorder_captures_metric_deltas():
    telemetry.reset()
    telemetry.enable()
    rec = FlightRecorder(max_events=32, snapshot_interval_s=0.0).install()
    try:
        c = telemetry.counter("delta.work")
        c.inc(3)
        with telemetry.span("tick"):
            pass
        entries = [e for e in list(rec._ring) if e[0] == "metrics"]
        assert entries and entries[-1][2].get("delta.work") == 3.0
        # unchanged registry -> no new delta entry on the next span
        n = len(entries)
        with telemetry.span("tick2"):
            pass
        entries = [e for e in list(rec._ring) if e[0] == "metrics"]
        assert len(entries) == n
    finally:
        rec.uninstall()
        telemetry.disable()
        telemetry.reset()


def test_recorder_not_installed_costs_one_none_check():
    """No recorder: spans record as before (tracer.flight is None)."""
    telemetry.reset()
    telemetry.enable()
    try:
        assert telemetry.tracer().flight is None
        with telemetry.span("free"):
            pass
        assert telemetry.stage_attribution()["free"]["count"] == 1
    finally:
        telemetry.disable()
        telemetry.reset()


def test_sigterm_dump_main_thread(tmp_path):
    telemetry.reset()
    telemetry.enable()
    rec = FlightRecorder(max_events=16).install()
    path = tmp_path / "flight.json"
    restore = install_sigterm_dump(rec, path)
    try:
        with telemetry.span("doomed"):
            pass
        with pytest.raises(SystemExit) as ei:
            os.kill(os.getpid(), signal.SIGTERM)
            # signal delivery is asynchronous: give the interpreter
            # bytecode boundaries until the handler fires
            for _ in range(500):
                time.sleep(0.01)
        assert ei.value.code == 143
        dump = json.loads(path.read_text())
        assert dump["flight"]["reason"] == "SIGTERM"
        assert any(e.get("name") == "doomed"
                   for e in dump["traceEvents"])
    finally:
        restore()
        rec.uninstall()
        telemetry.disable()
        telemetry.reset()


def test_sigterm_install_from_worker_thread_degrades():
    rec = FlightRecorder()
    out = {}

    def worker():
        out["restore"] = install_sigterm_dump(rec, "/nonexistent")

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    out["restore"]()  # no-op restorer, callable
    # and the process handler was never touched
    assert signal.getsignal(signal.SIGTERM) in (
        signal.SIG_DFL, signal.default_int_handler) or True


# -- SLO tracking ----------------------------------------------------------

def test_parse_slo_latency_and_ratio():
    o = parse_slo("p99:serving.frontend.request_latency_seconds<=50ms")
    assert isinstance(o, LatencyObjective)
    assert o.quantile == pytest.approx(0.99)
    assert o.threshold_s == pytest.approx(0.05)
    assert o.histogram == "serving.frontend.request_latency_seconds"
    o2 = parse_slo("tail=p99.9:x.y<=200us")
    assert o2.name == "tail" and o2.threshold_s == pytest.approx(2e-4)
    assert parse_slo("p50:x.y<=1.5").threshold_s == pytest.approx(1.5)
    r = parse_slo("shed=ratio:serving.frontend.rejected/"
                  "serving.frontend.admitted+serving.frontend.rejected"
                  "<=0.02")
    assert isinstance(r, RatioObjective)
    assert r.name == "shed" and r.max_ratio == pytest.approx(0.02)
    assert r.denominators == ("serving.frontend.admitted",
                              "serving.frontend.rejected")
    for bad in ("p99:x.y", "p200:x.y<=1s", "ratio:x<=0.5",
                "nope:x.y<=1s", "Bad Name=p99:x.y<=1s",
                "p99:x.y<=50parsecs"):
        with pytest.raises(ValueError):
            parse_slo(bad)
    with pytest.raises(ValueError, match="duplicate"):
        SLOTracker(["p99:a.b<=1s", "p99:a.b<=2s"])


def test_latency_burn_rate_exact_at_bucket_bound():
    """Threshold ON a bucket bound: the fraction over it is exact (le
    semantics make the cumulative count at the bound precise)."""
    telemetry.reset()
    telemetry.enable()
    try:
        h = telemetry.histogram("slo.test_latency_seconds",
                                buckets=[0.01, 0.1, 1.0])
        for _ in range(9):
            h.observe(0.05)   # <= 0.1
        h.observe(0.5)        # > 0.1
        tracker = SLOTracker(["p90:slo.test_latency_seconds<=100ms"])
        out = tracker.evaluate()
        entry = out["p90_slo_test_latency_seconds"]
        # 10% of samples over 100ms against a 10% budget: burn == 1.0,
        # compliant (<=)
        assert entry["burn_rate"] == pytest.approx(1.0)
        assert entry["compliant"] is True
        # tighten to p99: same 10% overflow burns 10x budget
        strict = SLOTracker(["p99:slo.test_latency_seconds<=100ms"])
        e2 = strict.evaluate()["p99_slo_test_latency_seconds"]
        assert e2["burn_rate"] == pytest.approx(10.0)
        assert e2["compliant"] is False
        snap = telemetry.snapshot()
        assert snap["counters"][
            "slo.p99_slo_test_latency_seconds.violations"] == 1
        assert snap["gauges"][
            "slo.p99_slo_test_latency_seconds.burn_rate"] == \
            pytest.approx(10.0)
    finally:
        telemetry.disable()
        telemetry.reset()


def test_ratio_burn_and_no_traffic_is_compliant():
    telemetry.reset()
    telemetry.enable()
    try:
        tracker = SLOTracker(
            ["shed=ratio:t.rejected/t.admitted+t.rejected<=0.10"])
        # no traffic: burns nothing, compliant, burn None
        e = tracker.evaluate()["shed"]
        assert e["burn_rate"] is None and e["compliant"] is True
        telemetry.counter("t.admitted").inc(80)
        telemetry.counter("t.rejected").inc(20)  # 20% shed vs 10% budget
        e = tracker.evaluate()["shed"]
        assert e["current"] == pytest.approx(0.2)
        assert e["burn_rate"] == pytest.approx(2.0)
        assert e["compliant"] is False
        assert e["evaluations"] == 2 and e["violations"] == 1
    finally:
        telemetry.disable()
        telemetry.reset()


@pytest.mark.needs_f64
def test_slo_burn_under_induced_overload(rng):
    """Acceptance: an induced overload (admission bound far below the
    offered burst) moves the shed-rate SLO's burn counters the right
    way — compliant before, violating after."""
    from tests.test_serving_frontend import (
        _dataset,
        _game_model,
        _singles,
    )
    from photon_ml_tpu.serving import (
        BucketLadder,
        FrontendConfig,
        ServingFrontend,
    )

    import jax.numpy as jnp

    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    telemetry.reset()
    telemetry.enable()
    try:
        tracker = SLOTracker(
            ["shed=ratio:serving.frontend.rejected/"
             "serving.frontend.admitted+serving.frontend.rejected"
             "<=0.05"])
        fe = ServingFrontend({"default": gm}, dtype=jnp.float64,
                             ladder=BucketLadder(min_rows=8, max_rows=64),
                             config=FrontendConfig(coalesce_window_s=0.05,
                                                   max_pending=4))
        reqs = _singles(950, 16)
        # closed-loop at concurrency 2 <= max_pending: nothing sheds
        fe.replay(reqs, concurrency=2)
        before = tracker.evaluate()["shed"]
        assert before["compliant"] is True
        # burst: all 16 at t=0 against max_pending=4 -> 12 shed (75%)
        _, info = fe.replay(reqs, arrivals=[0.0] * len(reqs))
        assert info["shed"] == 12
        after = tracker.evaluate()["shed"]
        assert after["compliant"] is False
        assert after["burn_rate"] > 1.0
        assert after["violations"] == before["violations"] + 1
        snap = telemetry.snapshot()
        assert snap["counters"]["slo.shed.violations"] == 1
        assert snap["counters"]["slo.shed.evaluations"] == 2
    finally:
        telemetry.disable()
        telemetry.reset()
