"""Optimizer tests, following the reference's pattern of optimizing known
convex functions (photon-ml/src/test/scala/.../optimization/LBFGSTest.scala,
OWLQNTest.scala, TRONTest.scala with TestObjective) plus cross-checks
against scipy on real GLM fits.
"""

import numpy as np

from tests.conftest import gold
import jax
import jax.numpy as jnp
import pytest
import scipy.optimize

from photon_ml_tpu.ops import GLMObjective, DenseFeatures, LogisticLoss
from photon_ml_tpu.ops.glm_objective import make_batch
from photon_ml_tpu.optimization import (
    ConvergenceReason,
    minimize_lbfgs,
    minimize_owlqn,
    minimize_tron,
)

CENTER = np.asarray([1.0, -2.0, 3.0, 0.5, -0.25])


def quad(x, scale):
    """The reference's TestObjective shape: sum_i s_i (x_i - c_i)^2."""
    d = x - jnp.asarray(CENTER, x.dtype)
    return jnp.sum(scale * d * d)


SCALES = jnp.asarray([1.0, 2.0, 0.5, 4.0, 1.5])


@pytest.mark.parametrize("minimize", [minimize_lbfgs, minimize_tron],
                         ids=["lbfgs", "tron"])
def test_quadratic_exact(minimize):
    res = minimize(quad, jnp.zeros(5), args=(SCALES,), tol=1e-10)
    np.testing.assert_allclose(np.asarray(res.x), CENTER, atol=1e-6)
    assert res.reason_enum() in (
        ConvergenceReason.GRADIENT_CONVERGED,
        ConvergenceReason.FUNCTION_VALUES_CONVERGED,
    )
    assert float(res.value) < 1e-10


def test_lbfgs_max_iterations_reason():
    res = minimize_lbfgs(quad, jnp.zeros(5), args=(SCALES,), max_iter=2,
                         tol=1e-14)
    assert res.reason_enum() == ConvergenceReason.MAX_ITERATIONS
    assert int(res.iterations) == 2


def test_value_history_is_monotone_nonincreasing():
    res = minimize_lbfgs(quad, jnp.zeros(5), args=(SCALES,), tol=1e-10)
    k = int(res.iterations)
    hist = np.asarray(res.value_history)[: k + 1]
    assert np.all(np.isfinite(hist))
    assert np.all(np.diff(hist) <= 1e-12)


def _logistic_problem(rng, n=200, d=8):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    w_true = rng.normal(0, 1, d)
    y = (rng.random(n) < 1 / (1 + np.exp(-x @ w_true))).astype(np.float64)
    return x, y


@pytest.mark.parametrize("minimize", [minimize_lbfgs, minimize_tron],
                         ids=["lbfgs", "tron"])
def test_logistic_fit_matches_scipy(minimize, rng):
    x, y = _logistic_problem(rng)
    l2 = 0.5
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), y)

    fun = lambda w, b: obj.value(w, b, l2)
    res = minimize(fun, jnp.zeros(8), args=(batch,), tol=1e-9)

    def np_obj(w):
        z = x @ w
        return (np.sum(np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0) - y * z)
                + 0.5 * l2 * w @ w)

    ref = scipy.optimize.minimize(np_obj, np.zeros(8), method="L-BFGS-B",
                                  options={"ftol": 1e-14, "gtol": 1e-10})
    np.testing.assert_allclose(float(res.value), ref.fun,
                               rtol=gold(1e-8, f32_floor=1e-4))
    np.testing.assert_allclose(np.asarray(res.x), ref.x,
                               atol=gold(2e-4, f32_floor=5e-3))


def test_box_constraints_match_scipy(rng):
    x, y = _logistic_problem(rng)
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), y)
    lo = np.full(8, -0.1)
    hi = np.full(8, 0.25)
    fun = lambda w, b: obj.value(w, b, 0.0)
    res = minimize_lbfgs(fun, jnp.zeros(8), args=(batch,), tol=1e-10,
                         lower_bounds=lo, upper_bounds=hi)
    assert np.all(np.asarray(res.x) >= lo - gold(1e-12, f32_floor=1e-6))
    assert np.all(np.asarray(res.x) <= hi + gold(1e-12, f32_floor=1e-6))

    def np_obj(w):
        z = x @ w
        return np.sum(np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0) - y * z)

    ref = scipy.optimize.minimize(np_obj, np.zeros(8), method="L-BFGS-B",
                                  bounds=list(zip(lo, hi)),
                                  options={"ftol": 1e-14, "gtol": 1e-10})
    # Naive per-step projection (same scheme as the reference, LBFGS.scala:77)
    # stalls slightly vs a true bound-constrained method — allow 1e-4 rel.
    assert float(res.value) >= ref.fun - gold(1e-9, f32_floor=1e-4)
    np.testing.assert_allclose(float(res.value), ref.fun, rtol=1e-4)


def test_tron_box_constraints(rng):
    res = minimize_tron(quad, jnp.zeros(5), args=(SCALES,), tol=1e-10,
                        lower_bounds=np.full(5, -1.0),
                        upper_bounds=np.full(5, 1.0))
    # Optimum of the constrained problem is the clipped center.
    np.testing.assert_allclose(np.asarray(res.x), np.clip(CENTER, -1, 1),
                               atol=1e-5)


def test_owlqn_l1_optimality(rng):
    """KKT check: at the OWL-QN solution, |grad_j| <= l1 where x_j == 0 and
    grad_j + l1*sign(x_j) ~= 0 where x_j != 0."""
    x, y = _logistic_problem(rng, n=300, d=10)
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), y)
    l1 = 8.0
    fun = lambda w, b: obj.value(w, b, 0.0)
    res = minimize_owlqn(fun, jnp.zeros(10), args=(batch,), l1_weight=l1,
                         tol=1e-10, max_iter=300)
    w = np.asarray(res.x)
    g = np.asarray(jax.grad(fun)(res.x, batch))
    zero = w == 0
    assert np.any(zero), "l1=8 should zero out some coefficients"
    assert np.all(np.abs(g[zero]) <= l1 + gold(1e-4, f32_floor=1e-2))
    nz = ~zero
    np.testing.assert_allclose(g[nz] + l1 * np.sign(w[nz]),
                               np.zeros(nz.sum()),
                               atol=gold(2e-3, f32_floor=2e-2))


def test_owlqn_zero_l1_matches_lbfgs(rng):
    x, y = _logistic_problem(rng)
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), y)
    fun = lambda w, b: obj.value(w, b, 0.3)
    r1 = minimize_owlqn(fun, jnp.zeros(8), args=(batch,), l1_weight=0.0,
                        tol=1e-10)
    r2 = minimize_lbfgs(fun, jnp.zeros(8), args=(batch,), tol=1e-10)
    np.testing.assert_allclose(float(r1.value), float(r2.value), rtol=1e-7)


def test_owlqn_per_coordinate_l1_exempts_intercept(rng):
    x, y = _logistic_problem(rng, n=300, d=6)
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), y)
    l1 = np.full(6, 500.0)  # far above any sustainable data gradient
    l1[-1] = 0.0  # intercept unpenalized
    fun = lambda w, b: obj.value(w, b, 0.0)
    res = minimize_owlqn(fun, jnp.zeros(6), args=(batch,), l1_weight=l1,
                         tol=1e-12, max_iter=300)
    w = np.asarray(res.x)
    assert np.all(w[:-1] == 0.0), "huge l1 should kill all non-intercept"
    # Intercept solves mean(sigmoid(b)) = mean(y).
    expect_b = np.log(y.mean() / (1 - y.mean()))
    np.testing.assert_allclose(w[-1], expect_b, atol=5e-3)


@pytest.mark.parametrize("minimize,kw", [
    (minimize_lbfgs, {}),
    (minimize_tron, {}),
], ids=["lbfgs", "tron"])
def test_vmap_batched_solves_match_individual(minimize, kw, rng):
    """The random-effect execution mode: one batched solve over an entity
    axis must equal per-entity solves (SURVEY §2.3 entity sharding)."""
    B, n, d = 5, 40, 4
    xs = rng.normal(0, 1, (B, n, d))
    ys = (rng.random((B, n)) < 0.5).astype(np.float64)
    obj = GLMObjective(LogisticLoss)

    def fit(x, y):
        batch = make_batch(DenseFeatures(x), y)
        fun = lambda w, b: obj.value(w, b, 0.1)
        return minimize(fun, jnp.zeros(d), args=(batch,), tol=1e-9, **kw)

    batched = jax.vmap(fit)(jnp.asarray(xs), jnp.asarray(ys))
    for b in range(B):
        single = fit(jnp.asarray(xs[b]), jnp.asarray(ys[b]))
        np.testing.assert_allclose(float(batched.value[b]),
                                   float(single.value), rtol=1e-7)
        np.testing.assert_allclose(np.asarray(batched.x[b]),
                                   np.asarray(single.x),
                                   atol=gold(1e-4, f32_floor=2e-3))


def test_owlqn_vmap(rng):
    B, n, d = 3, 60, 5
    xs = rng.normal(0, 1, (B, n, d))
    ys = (rng.random((B, n)) < 0.5).astype(np.float64)
    obj = GLMObjective(LogisticLoss)

    def fit(x, y):
        batch = make_batch(DenseFeatures(x), y)
        fun = lambda w, b: obj.value(w, b, 0.0)
        return minimize_owlqn(fun, jnp.zeros(d), args=(batch,), l1_weight=2.0,
                              tol=1e-9, max_iter=200)

    batched = jax.vmap(fit)(jnp.asarray(xs), jnp.asarray(ys))
    for b in range(B):
        single = fit(jnp.asarray(xs[b]), jnp.asarray(ys[b]))
        np.testing.assert_allclose(float(batched.value[b]),
                                   float(single.value), rtol=1e-6)


def test_already_optimal_start():
    res = minimize_lbfgs(quad, jnp.asarray(CENTER), args=(SCALES,))
    assert res.reason_enum() in (ConvergenceReason.GRADIENT_CONVERGED,
                                 ConvergenceReason.FUNCTION_VALUES_CONVERGED)
    assert int(res.iterations) <= 1
    np.testing.assert_allclose(np.asarray(res.x), CENTER, atol=1e-12)


def test_compact_direction_matches_two_loop(rng):
    """The Byrd-Nocedal compact representation is algebraically identical
    to the two-loop recursion — check on random histories: empty, partial
    (leading zero slots), and full, with curvature-positive pairs."""
    from photon_ml_tpu.optimization.lbfgs import (
        _empty_history,
        compact_direction,
        two_loop_direction,
        update_history,
    )

    d, m = 17, 6
    for n_pairs in (0, 1, 3, 6, 9):
        hist = _empty_history(d, m, jnp.float64)
        for _ in range(n_pairs):
            s = jnp.asarray(rng.normal(0, 1, d))
            y = s * rng.uniform(0.5, 2.0) + 0.1 * jnp.asarray(
                rng.normal(0, 1, d))  # keep s.y > 0
            hist = update_history(hist, s, y)
        g = jnp.asarray(rng.normal(0, 1, d))
        np.testing.assert_allclose(
            np.asarray(compact_direction(g, hist)),
            np.asarray(two_loop_direction(g, hist)),
            rtol=1e-9, atol=1e-11)
