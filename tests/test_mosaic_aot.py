"""Deviceless Mosaic compile guard: one fused-kernel variant must
AOT-compile for a real v5e target using the image's local libtpu
(no chip needed — see dev_scripts/mosaic_aot_check.py for the full
matrix). Interpret-mode parity cannot catch Mosaic legalization
regressions (e.g. vector<i1> loop carries, KERNEL.md constraint #6);
this keeps at least one real-compiler compile in the suite."""

import functools

import numpy as np
import pytest


def _topology():
    from photon_ml_tpu.utils.aot import v5e_topology

    try:
        return v5e_topology()
    except Exception as e:  # noqa: BLE001 - no libtpu / locked
        pytest.skip(f"v5e compile-only client unavailable: {e}")


def test_entity_kernel_compiles_for_v5e():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.pallas_entity_solver import pallas_entity_lbfgs
    from photon_ml_tpu.types import TaskType

    if jax.config.jax_enable_x64:
        # jax 0.9.0: x64 canonicalization recurses infinitely when
        # lowering this program for the compile-only TPU client; the
        # f32 suite config (and dev_scripts/mosaic_aot_check.py, which
        # runs outside the conftest) covers the compile.
        pytest.skip("v5e AOT lowering hits a JAX recursion bug under x64")
    topo = _topology()
    sh = NamedSharding(Mesh(np.array(topo.devices[:1]), ("x",)),
                       PartitionSpec())
    e, r, d = 128, 4, 4

    def arg(shape, dt=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dt, sharding=sh)

    # max_line_search > 8 exercises the tail while_loop (the construct
    # that regressed); norm+bounds exercises the widest variant.
    fn = functools.partial(
        pallas_entity_lbfgs, loss_for_task(TaskType.LOGISTIC_REGRESSION),
        max_iter=5, tol=1e-6, mode="lbfgs", max_line_search=12)
    compiled = jax.jit(fn).lower(
        arg((e, r, d)), arg((e, r)), arg((e, r)), arg((e, r)),
        arg((e, d)), arg(()), arg(()),
        factors=arg((e, d)), shifts=arg((e, d)),
        lower=arg((e, d)), upper=arg((e, d))).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0
