"""Task x optimizer x regularization training matrix — the analog of the
reference's DriverTest per-optimizer/per-regularization matrices
(photon-ml/src/integTest/.../DriverTest.scala, 1034 LoC): every valid combo
trains to a finite, genuinely-fit model; invalid combos raise."""

import numpy as np
import pytest

from photon_ml_tpu.estimators.model_training import train_glm_models
from photon_ml_tpu.optimization.config import (
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.types import TaskType

N, D = 250, 6


def _data(task, rng):
    x = rng.normal(size=(N, D))
    x[:, -1] = 1.0
    w = rng.normal(size=D) * 0.6
    z = x @ w
    if task == TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(z, -4, 3))).astype(float)
    elif task == TaskType.LINEAR_REGRESSION:
        y = z + rng.normal(0, 0.2, N)
    else:  # logistic / SVM: binary
        y = (rng.random(N) < 1 / (1 + np.exp(-z))).astype(float)
    return x, y, w


VALID = []
for task in TaskType:
    for opt in OptimizerType:
        for reg in RegularizationType:
            if opt == OptimizerType.TRON and reg in (
                    RegularizationType.L1, RegularizationType.ELASTIC_NET):
                continue  # TRON has no L1 machinery (reference: same)
            if (opt == OptimizerType.TRON
                    and task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
                continue  # once-differentiable loss
            VALID.append((task, opt, reg))


@pytest.mark.parametrize(
    "task,opt,reg", VALID,
    ids=[f"{t.value}-{o.value}-{r.value}" for t, o, r in VALID])
def test_matrix_combo_trains(task, opt, reg, rng):
    x, y, w_true = _data(task, rng)
    ctx = RegularizationContext(
        reg,
        elastic_net_alpha=(0.5 if reg == RegularizationType.ELASTIC_NET
                           else None))
    lam = [1.0] if reg != RegularizationType.NONE else [0.0]
    trained = train_glm_models(
        x, y, task, regularization_weights=lam,
        regularization_context=ctx, optimizer_type=opt,
        max_iterations=60, tolerance=1e-8)[0]
    coefs = np.asarray(trained.model.coefficients.means)
    assert np.all(np.isfinite(coefs))
    assert np.isfinite(float(trained.result.value))
    # The fit recovers the generating direction.
    corr = np.corrcoef(coefs[:-1], w_true[:-1])[0, 1]
    assert corr > 0.7, (task, opt, reg, corr)


def test_tron_l1_rejected(rng):
    x, y, _ = _data(TaskType.LOGISTIC_REGRESSION, rng)
    with pytest.raises(ValueError, match="L1"):
        train_glm_models(
            x, y, TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[1.0],
            regularization_context=RegularizationContext(
                RegularizationType.L1),
            optimizer_type=OptimizerType.TRON, max_iterations=5)


def test_tron_smoothed_hinge_rejected(rng):
    x, y, _ = _data(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM, rng)
    with pytest.raises(ValueError, match="twice-differentiable"):
        train_glm_models(
            x, y, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
            regularization_weights=[1.0],
            optimizer_type=OptimizerType.TRON, max_iterations=5)


def test_l1_produces_sparser_models_with_larger_lambda(rng):
    x, y, _ = _data(TaskType.LOGISTIC_REGRESSION, rng)
    trained = train_glm_models(
        x, y, TaskType.LOGISTIC_REGRESSION,
        regularization_weights=[20.0, 0.01],
        regularization_context=RegularizationContext(RegularizationType.L1),
        max_iterations=100, tolerance=1e-9)
    nnz = [int(np.sum(np.abs(np.asarray(t.model.coefficients.means))
                      > 1e-8)) for t in trained]
    assert nnz[0] < nnz[1], nnz  # grid order preserved: [20.0, 0.01]
