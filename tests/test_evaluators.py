"""Evaluator tests (reference: ml/evaluation/*Test.scala)."""

import numpy as np
import pytest

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation import build_evaluator
from photon_ml_tpu.evaluation.evaluators import (
    AreaUnderROCCurveEvaluator,
    RMSEEvaluator,
    ShardedPrecisionAtKEvaluator,
    area_under_roc_curve,
)
import scipy.sparse as sp


def brute_force_auc(scores, labels):
    pos = scores[labels >= 0.5]
    neg = scores[labels < 0.5]
    wins = sum((p > n) + 0.5 * (p == n) for p in pos for n in neg)
    return wins / (len(pos) * len(neg))


def test_auc_matches_brute_force(rng):
    scores = rng.normal(0, 1, 60)
    scores[10:20] = scores[0]  # inject ties
    labels = (rng.random(60) < 0.5).astype(float)
    np.testing.assert_allclose(
        area_under_roc_curve(scores, labels),
        brute_force_auc(scores, labels), rtol=1e-12)


def test_auc_perfect_and_reverse():
    s = np.asarray([0.1, 0.2, 0.8, 0.9])
    y = np.asarray([0.0, 0.0, 1.0, 1.0])
    assert area_under_roc_curve(s, y) == 1.0
    assert area_under_roc_curve(-s, y) == 0.0
    assert np.isnan(area_under_roc_curve(s, np.ones(4)))


def test_auc_weighted_equals_replication(rng):
    scores = rng.normal(0, 1, 20)
    labels = (rng.random(20) < 0.5).astype(float)
    weights = rng.integers(1, 4, 20).astype(float)
    rep_scores = np.repeat(scores, weights.astype(int))
    rep_labels = np.repeat(labels, weights.astype(int))
    np.testing.assert_allclose(
        area_under_roc_curve(scores, labels, weights),
        brute_force_auc(rep_scores, rep_labels), rtol=1e-12)


def test_rmse_and_ordering():
    ev = RMSEEvaluator()
    v = ev.evaluate(np.asarray([1.0, 2.0]), np.asarray([0.0, 0.0]))
    np.testing.assert_allclose(v, np.sqrt(2.5))
    assert ev.better_than(1.0, 2.0) and not ev.better_than(2.0, 1.0)
    auc = AreaUnderROCCurveEvaluator()
    assert auc.better_than(0.9, 0.8) and auc.better_than(0.5, None)


def test_sharded_evaluators(rng):
    n = 40
    queries = np.repeat(np.arange(4), 10)
    y = (rng.random(n) < 0.5).astype(float)
    scores = y + rng.normal(0, 0.1, n)  # nearly perfect
    data = GameDataset.build(
        responses=y, feature_shards={"s": sp.csr_matrix(np.ones((n, 1)))},
        ids={"queryId": queries.astype(str)})
    ev = build_evaluator("AUC:queryId")
    v = ev.evaluate_dataset(scores, data)
    assert v > 0.95
    p1 = ShardedPrecisionAtKEvaluator(k=1, id_type="queryId")
    assert p1.evaluate_dataset(scores, data) == 1.0
    # precision@big-k -> base positive rate per group
    pk = build_evaluator("PRECISION@10:queryId")
    np.testing.assert_allclose(pk.evaluate_dataset(scores, data), y.mean(),
                               rtol=1e-12)


def test_build_evaluator_specs():
    assert build_evaluator("auc").name == "AUC"
    assert build_evaluator("RMSE").name == "RMSE"
    assert build_evaluator("LOGISTIC_LOSS").name == "LOGISTIC_LOSS"
    assert build_evaluator("AUC:userId").id_type == "userId"
    ev = build_evaluator("PRECISION@5:docId")
    assert ev.k == 5 and ev.id_type == "docId"
    with pytest.raises(ValueError):
        build_evaluator("NDCG@3")


def test_pr_auc_perfect_and_random():
    from photon_ml_tpu.evaluation.evaluators import (
        area_under_precision_recall,
        peak_f1_score,
    )

    scores = np.asarray([0.9, 0.8, 0.2, 0.1])
    labels = np.asarray([1.0, 1.0, 0.0, 0.0])
    assert area_under_precision_recall(scores, labels) == pytest.approx(1.0)
    assert peak_f1_score(scores, labels) == pytest.approx(1.0)
    # all-negative labels -> undefined
    assert np.isnan(area_under_precision_recall(scores, np.zeros(4)))


def test_pr_auc_matches_bruteforce():
    from photon_ml_tpu.evaluation.evaluators import (
        area_under_precision_recall,
        peak_f1_score,
    )

    rng = np.random.default_rng(5)
    scores = rng.normal(size=200)
    labels = (rng.random(200) < 1 / (1 + np.exp(-scores))).astype(float)
    w = rng.random(200) + 0.5

    # Brute force: P/R at every distinct threshold, trapezoid with the
    # MLlib-style (0, p_first) start point.
    ts = np.unique(scores)[::-1]
    ps, rs = [], []
    total_pos = w[labels == 1].sum()
    for t in ts:
        sel = scores >= t
        tp = w[sel & (labels == 1)].sum()
        ps.append(tp / w[sel].sum())
        rs.append(tp / total_pos)
    expected = np.trapezoid(np.r_[ps[0], ps], np.r_[0.0, rs])
    got = area_under_precision_recall(scores, labels, w)
    assert got == pytest.approx(expected, rel=1e-12)

    f1s = [2 * p * r / (p + r) for p, r in zip(ps, rs) if p + r > 0]
    assert peak_f1_score(scores, labels, w) == pytest.approx(max(f1s),
                                                             rel=1e-12)


def test_evaluate_glm_includes_pr_metrics():
    from photon_ml_tpu.evaluation.validation import evaluate_glm
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    scores = rng.normal(size=100)
    labels = (rng.random(100) < 0.5).astype(float)
    m = evaluate_glm(TaskType.LOGISTIC_REGRESSION, scores, labels)
    assert {"PR_AUC", "PEAK_F1"} <= set(m)
    assert 0.0 <= m["PR_AUC"] <= 1.0 and 0.0 <= m["PEAK_F1"] <= 1.0


def test_sharded_vectorized_matches_per_group_loop(rng):
    """The sort-once segmented implementations must agree with a brute
    per-group loop on weighted data with ties, skewed group sizes, and
    single-class groups (which AUC must skip)."""
    from photon_ml_tpu.data.game_data import group_rows_by_code
    from photon_ml_tpu.evaluation.evaluators import (
        area_under_roc_curve,
        sharded_auc,
        sharded_precision_at_k,
    )

    n = 3000
    codes = np.sort(rng.integers(0, 120, n)).astype(np.int32)
    y = (rng.random(n) < 0.4).astype(float)
    # quantized scores -> plenty of ties, incl. cross-group
    pred = np.round(rng.normal(0, 1, n), 1)
    w = rng.integers(1, 4, n).astype(float)
    # a few guaranteed single-class groups
    y[codes == 0] = 1.0
    y[codes == 1] = 0.0

    groups = group_rows_by_code(codes)
    auc_vals = []
    for rows in groups:
        v = area_under_roc_curve(pred[rows], y[rows], w[rows])
        if not np.isnan(v):
            auc_vals.append(v)
    np.testing.assert_allclose(sharded_auc(pred, y, w, codes),
                               np.mean(auc_vals), rtol=1e-12)

    for k in (1, 3, 10):
        pk_vals = []
        for rows in groups:
            top = rows[np.argsort(-pred[rows], kind="stable")[:k]]
            pk_vals.append(float((y[top] >= 0.5).mean()))
        np.testing.assert_allclose(
            sharded_precision_at_k(pred, y, codes, k),
            np.mean(pk_vals), rtol=1e-12)


def test_sharded_auc_is_fast():
    """200k rows / 5k groups in well under the 100ms budget (the old
    per-group python loop took seconds at this shape)."""
    import time

    from photon_ml_tpu.evaluation.evaluators import sharded_auc

    rng2 = np.random.default_rng(3)
    n = 200_000
    codes = np.sort(rng2.integers(0, 5000, n)).astype(np.int32)
    y = (rng2.random(n) < 0.5).astype(float)
    pred = rng2.normal(0, 1, n)
    w = np.ones(n)
    sharded_auc(pred, y, w, codes)  # warm
    # Best of 3: the budget guards against an accidental return to the
    # per-group python loop (seconds), not against transient host load
    # (this 1-core machine runs concurrent benchmark jobs in CI).
    best = min(_timed(lambda: sharded_auc(pred, y, w, codes))
               for _ in range(3))
    assert best < 0.25, best


def _timed(fn):
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
