"""Evaluator tests (reference: ml/evaluation/*Test.scala)."""

import numpy as np
import pytest

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation import build_evaluator
from photon_ml_tpu.evaluation.evaluators import (
    AreaUnderROCCurveEvaluator,
    RMSEEvaluator,
    ShardedPrecisionAtKEvaluator,
    area_under_roc_curve,
)
import scipy.sparse as sp


def brute_force_auc(scores, labels):
    pos = scores[labels >= 0.5]
    neg = scores[labels < 0.5]
    wins = sum((p > n) + 0.5 * (p == n) for p in pos for n in neg)
    return wins / (len(pos) * len(neg))


def test_auc_matches_brute_force(rng):
    scores = rng.normal(0, 1, 60)
    scores[10:20] = scores[0]  # inject ties
    labels = (rng.random(60) < 0.5).astype(float)
    np.testing.assert_allclose(
        area_under_roc_curve(scores, labels),
        brute_force_auc(scores, labels), rtol=1e-12)


def test_auc_perfect_and_reverse():
    s = np.asarray([0.1, 0.2, 0.8, 0.9])
    y = np.asarray([0.0, 0.0, 1.0, 1.0])
    assert area_under_roc_curve(s, y) == 1.0
    assert area_under_roc_curve(-s, y) == 0.0
    assert np.isnan(area_under_roc_curve(s, np.ones(4)))


def test_auc_weighted_equals_replication(rng):
    scores = rng.normal(0, 1, 20)
    labels = (rng.random(20) < 0.5).astype(float)
    weights = rng.integers(1, 4, 20).astype(float)
    rep_scores = np.repeat(scores, weights.astype(int))
    rep_labels = np.repeat(labels, weights.astype(int))
    np.testing.assert_allclose(
        area_under_roc_curve(scores, labels, weights),
        brute_force_auc(rep_scores, rep_labels), rtol=1e-12)


def test_rmse_and_ordering():
    ev = RMSEEvaluator()
    v = ev.evaluate(np.asarray([1.0, 2.0]), np.asarray([0.0, 0.0]))
    np.testing.assert_allclose(v, np.sqrt(2.5))
    assert ev.better_than(1.0, 2.0) and not ev.better_than(2.0, 1.0)
    auc = AreaUnderROCCurveEvaluator()
    assert auc.better_than(0.9, 0.8) and auc.better_than(0.5, None)


def test_sharded_evaluators(rng):
    n = 40
    queries = np.repeat(np.arange(4), 10)
    y = (rng.random(n) < 0.5).astype(float)
    scores = y + rng.normal(0, 0.1, n)  # nearly perfect
    data = GameDataset.build(
        responses=y, feature_shards={"s": sp.csr_matrix(np.ones((n, 1)))},
        ids={"queryId": queries.astype(str)})
    ev = build_evaluator("AUC:queryId")
    v = ev.evaluate_dataset(scores, data)
    assert v > 0.95
    p1 = ShardedPrecisionAtKEvaluator(k=1, id_type="queryId")
    assert p1.evaluate_dataset(scores, data) == 1.0
    # precision@big-k -> base positive rate per group
    pk = build_evaluator("PRECISION@10:queryId")
    np.testing.assert_allclose(pk.evaluate_dataset(scores, data), y.mean(),
                               rtol=1e-12)


def test_build_evaluator_specs():
    assert build_evaluator("auc").name == "AUC"
    assert build_evaluator("RMSE").name == "RMSE"
    assert build_evaluator("LOGISTIC_LOSS").name == "LOGISTIC_LOSS"
    assert build_evaluator("AUC:userId").id_type == "userId"
    ev = build_evaluator("PRECISION@5:docId")
    assert ev.k == 5 and ev.id_type == "docId"
    with pytest.raises(ValueError):
        build_evaluator("NDCG@3")
