"""Unit tests for pointwise losses vs closed forms and numeric derivatives.

Mirrors the reference's LogisticLossFunctionTest / PoissonLossFunctionTest
style (photon-ml/src/test/scala/.../function/glm/*Test.scala): check values
against independent formulas and derivatives against finite differences.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tests.conftest import GOLD_RTOL

from photon_ml_tpu.ops.losses import (
    LogisticLoss,
    SquaredLoss,
    PoissonLoss,
    SmoothedHingeLoss,
)

ALL_LOSSES = [LogisticLoss, SquaredLoss, PoissonLoss, SmoothedHingeLoss]


def _labels_for(loss, n, rng):
    if loss is PoissonLoss:
        return rng.poisson(2.0, n).astype(np.float64)
    if loss is SquaredLoss:
        return rng.normal(0, 2, n)
    return (rng.random(n) < 0.5).astype(np.float64)


@pytest.mark.needs_f64  # FD with eps=1e-6 only resolves in f64
@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_d1_matches_finite_difference(loss, rng):
    z = jnp.asarray(rng.normal(0, 2, 64))
    y = jnp.asarray(_labels_for(loss, 64, rng))
    eps = 1e-6
    fd = (loss.loss(z + eps, y) - loss.loss(z - eps, y)) / (2 * eps)
    np.testing.assert_allclose(loss.d1(z, y), fd, rtol=1e-4, atol=1e-6)


@pytest.mark.needs_f64
@pytest.mark.parametrize(
    "loss", [LogisticLoss, SquaredLoss, PoissonLoss], ids=lambda l: l.name
)
def test_d2_matches_finite_difference(loss, rng):
    z = jnp.asarray(rng.normal(0, 2, 64))
    y = jnp.asarray(_labels_for(loss, 64, rng))
    eps = 1e-6
    fd = (loss.d1(z + eps, y) - loss.d1(z - eps, y)) / (2 * eps)
    np.testing.assert_allclose(loss.d2(z, y), fd, rtol=1e-4, atol=1e-6)


def test_logistic_closed_form():
    z = jnp.asarray([0.0, 1.0, -1.0, 30.0, -30.0])
    y = jnp.asarray([1.0, 0.0, 1.0, 0.0, 1.0])
    expected = np.log1p(np.exp(np.asarray(z))) - np.asarray(y) * np.asarray(z)
    np.testing.assert_allclose(LogisticLoss.loss(z, y), expected,
                               rtol=GOLD_RTOL)


def test_logistic_extreme_margins_are_stable():
    z = jnp.asarray([1e4, -1e4])
    y = jnp.asarray([0.0, 1.0])
    vals = np.asarray(LogisticLoss.loss(z, y))
    assert np.all(np.isfinite(vals))
    # l(z, 0) -> z for large z ; l(z, 1) -> -z for very negative z
    np.testing.assert_allclose(vals, [1e4, 1e4], rtol=1e-6)
    assert np.all(np.isfinite(np.asarray(LogisticLoss.d1(z, y))))


def test_squared_closed_form():
    z = jnp.asarray([3.0, -2.0])
    y = jnp.asarray([1.0, 1.0])
    np.testing.assert_allclose(SquaredLoss.loss(z, y), [2.0, 4.5])
    np.testing.assert_allclose(SquaredLoss.d1(z, y), [2.0, -3.0])
    np.testing.assert_allclose(SquaredLoss.d2(z, y), [1.0, 1.0])


def test_poisson_closed_form():
    z = jnp.asarray([0.0, 1.0])
    y = jnp.asarray([2.0, 0.0])
    np.testing.assert_allclose(PoissonLoss.loss(z, y), [1.0, np.e],
                               rtol=GOLD_RTOL)


def test_smoothed_hinge_segments():
    # y=1 -> t=z. Segments: t<=0: 1/2 - t; 0<t<1: (1-t)^2/2; t>=1: 0.
    y = jnp.ones(4)
    z = jnp.asarray([-1.0, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(
        SmoothedHingeLoss.loss(z, y), [1.5, 0.5, 0.125, 0.0], rtol=1e-12
    )
    np.testing.assert_allclose(
        SmoothedHingeLoss.d1(z, y), [-1.0, -1.0, -0.5, 0.0], rtol=1e-12
    )
    # y=0 mirrors through t = -z.
    np.testing.assert_allclose(
        SmoothedHingeLoss.loss(-z, jnp.zeros(4)), [1.5, 0.5, 0.125, 0.0],
        rtol=1e-12,
    )


def test_losses_jit_and_grad():
    z = jnp.asarray([0.3, -0.7])
    y = jnp.asarray([1.0, 0.0])
    for loss in ALL_LOSSES:
        total = jax.jit(lambda z: jnp.sum(loss.loss(z, y)))
        g = jax.grad(lambda z: jnp.sum(loss.loss(z, y)))(z)
        if loss.twice_differentiable:
            np.testing.assert_allclose(g, loss.d1(z, y), rtol=GOLD_RTOL)
        assert np.isfinite(float(total(z)))
