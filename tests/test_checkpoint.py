"""Checkpoint/resume + fault-injection tests for coordinate descent
(SURVEY.md §5: the reference has no mid-training checkpointing; the TPU
build adds orbax-style state saves every k coordinate updates and a
fault-injection test that kills and resumes mid-descent)."""

import numpy as np
import pytest

from photon_ml_tpu.algorithm import CoordinateDescent
from photon_ml_tpu.evaluation import build_evaluator
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.checkpoint import (
    CheckpointState,
    all_checkpoint_steps,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)

from tests.test_coordinate_descent import build_coordinates, make_glmix_data


def _final_coefs(result):
    fe = result.model.get_model("fixed")
    return np.asarray(fe.glm.coefficients.means)


def test_checkpoint_save_load_roundtrip(tmp_path):
    state = CheckpointState(
        step=3, models={"a": np.arange(4.0)},
        objective_history=[3.0, 2.0, 1.0], validation_history=[{"AUC": 0.7}],
        best_metric=0.7, best_models=None, timings={"a": 1.5})
    save_checkpoint(tmp_path, state)
    loaded = load_checkpoint(latest_checkpoint(tmp_path))
    assert loaded.step == 3
    np.testing.assert_array_equal(loaded.models["a"], np.arange(4.0))
    assert loaded.objective_history == [3.0, 2.0, 1.0]
    assert loaded.best_metric == 0.7


def test_checkpoint_retention_and_atomicity(tmp_path):
    for step in (1, 2, 3, 4):
        save_checkpoint(tmp_path, CheckpointState(
            step=step, models={}, objective_history=[],
            validation_history=[], best_metric=None, best_models=None,
            timings={}), keep=2)
    assert sorted(all_checkpoint_steps(tmp_path)) == [3, 4]
    assert not list(tmp_path.glob("*.tmp"))
    # A stray truncated tmp file never shadows a real checkpoint.
    (tmp_path / "ckpt-00000009.tmp").write_bytes(b"garbage")
    assert latest_checkpoint(tmp_path).name == "ckpt-00000004.pkl"


@pytest.mark.slow
def test_resume_matches_uninterrupted_run(rng, tmp_path):
    """Kill after a mid-descent checkpoint; the resumed run must reproduce
    the uninterrupted run (fold_in per-step keys make this exact)."""
    data, *_ = make_glmix_data(rng)

    # Uninterrupted reference run (no checkpointing).
    cd_ref = CoordinateDescent(build_coordinates(data),
                               TaskType.LOGISTIC_REGRESSION)
    ref = cd_ref.run(num_iterations=3, seed=11)

    # Fault-injected run: crash during iteration 2 (step 4 of 6). The hot
    # loop runs through fused jitted update fns, so the fault is injected
    # at the dispatch layer (the jit cache means a fault inside pure_update
    # would only fire while tracing).
    coords = build_coordinates(data)
    cd_crash = CoordinateDescent(coords, TaskType.LOGISTIC_REGRESSION)
    fns = cd_crash._fused_update_fns()
    original_update = fns["perUser"]
    calls = {"n": 0}

    def failing_update(*args):
        calls["n"] += 1
        if calls["n"] == 2:  # second perUser update = step 4
            raise RuntimeError("injected fault")
        return original_update(*args)

    fns["perUser"] = failing_update
    with pytest.raises(RuntimeError, match="injected fault"):
        cd_crash.run(num_iterations=3, seed=11, checkpoint_dir=tmp_path)
    # Steps 1..3 completed and were checkpointed before the crash.
    assert max(all_checkpoint_steps(tmp_path)) == 3

    # Fresh process-equivalent: new coordinates, resume from disk.
    cd_resume = CoordinateDescent(build_coordinates(data),
                                  TaskType.LOGISTIC_REGRESSION)
    resumed = cd_resume.run(num_iterations=3, seed=11,
                            checkpoint_dir=tmp_path)

    np.testing.assert_allclose(_final_coefs(resumed), _final_coefs(ref),
                               rtol=1e-6)
    assert len(resumed.objective_history) == len(ref.objective_history)
    np.testing.assert_allclose(resumed.objective_history,
                               ref.objective_history, rtol=1e-5)
    # Trackers are checkpointed too: pre-crash updates are not lost.
    assert len(resumed.trackers["fixed"]) == len(ref.trackers["fixed"])
    assert len(resumed.trackers["perUser"]) == len(ref.trackers["perUser"])


def test_resume_rejects_mismatched_configuration(rng, tmp_path):
    data, *_ = make_glmix_data(rng, n=200)
    cd = CoordinateDescent(build_coordinates(data),
                           TaskType.LOGISTIC_REGRESSION)
    cd.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path)
    cd2 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION)
    with pytest.raises(ValueError, match="different configuration"):
        cd2.run(num_iterations=1, seed=2, checkpoint_dir=tmp_path)
    with pytest.raises(ValueError, match="checkpoint_interval"):
        cd2.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path,
                checkpoint_interval=0)


def test_resume_survives_benign_tag_reordering(rng, tmp_path):
    """Checkpoint identity is a canonical hash: a mapping tag with a
    different insertion order is the SAME configuration and must resume;
    a changed updating sequence is a DIFFERENT one and must hard-error."""
    data, *_ = make_glmix_data(rng, n=200)
    tag = {"fixed": "10,1e-4,1.0,LBFGS,L2", "perUser": "5,1e-4,1.0,LBFGS,L2"}
    cd = CoordinateDescent(build_coordinates(data),
                           TaskType.LOGISTIC_REGRESSION)
    first = cd.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path,
                   checkpoint_tag=tag)

    reordered = dict(reversed(list(tag.items())))
    assert list(reordered) != list(tag)  # genuinely different insertion order
    cd2 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION)
    second = cd2.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path,
                     checkpoint_tag=reordered)  # must NOT raise
    np.testing.assert_allclose(_final_coefs(second), _final_coefs(first),
                               rtol=1e-7)

    # Changed updating sequence (list order is semantic) still rejects.
    coords = build_coordinates(data)
    swapped = {k: coords[k] for k in reversed(list(coords))}
    cd3 = CoordinateDescent(swapped, TaskType.LOGISTIC_REGRESSION)
    with pytest.raises(ValueError, match="different configuration"):
        cd3.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path,
                checkpoint_tag=tag)

    # A semantically different tag value rejects too.
    changed = dict(tag, fixed="99,1e-4,1.0,TRON,L2")
    cd4 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION)
    with pytest.raises(ValueError, match="different configuration"):
        cd4.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path,
                checkpoint_tag=changed)


def test_config_fingerprint_canonicalization():
    from photon_ml_tpu.utils.checkpoint import config_fingerprint

    a = {"x": 1, "y": {"b": 2, "a": 3}, "seq": ["f", "r"]}
    b = {"y": {"a": 3, "b": 2}, "seq": ["f", "r"], "x": 1}
    assert config_fingerprint(a) == config_fingerprint(b)
    # List order is semantic.
    c = dict(a, seq=["r", "f"])
    assert config_fingerprint(c) != config_fingerprint(a)


def test_legacy_string_tag_still_resumes(rng, tmp_path):
    """Checkpoints written when tags were flattened 'k=v;...' strings must
    resume under the equivalent mapping tag (and vice versa)."""
    from photon_ml_tpu.utils.checkpoint import meta_fingerprints

    tag_map = {"fixed": "10,1e-4,1.0,LBFGS,L2", "perUser": "5,..."}
    legacy = ";".join(f"{k}={v}" for k, v in sorted(tag_map.items()))
    old_meta = {"seed": 1, "coordinates": ["fixed", "perUser"],
                "taskType": "LOGISTIC_REGRESSION", "tag": legacy}
    new_meta = dict(old_meta, tag=tag_map)
    assert meta_fingerprints(old_meta) & meta_fingerprints(new_meta)

    # End-to-end: save under the legacy string, resume under the mapping.
    data, *_ = make_glmix_data(rng, n=200)
    cd = CoordinateDescent(build_coordinates(data),
                           TaskType.LOGISTIC_REGRESSION)
    cd.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path,
           checkpoint_tag=legacy)
    cd2 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION)
    cd2.run(num_iterations=1, seed=1, checkpoint_dir=tmp_path,
            checkpoint_tag=tag_map)  # must NOT raise


@pytest.mark.slow
def test_resume_preserves_best_model_and_validation(rng, tmp_path):
    data, *_ = make_glmix_data(rng, n=300)
    vdata, *_ = make_glmix_data(rng, n=120)
    ev = [build_evaluator("AUC")]

    cd1 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION,
                            validation_data=vdata,
                            validation_evaluators=ev)
    cd1.run(num_iterations=1, seed=5, checkpoint_dir=tmp_path)

    # Continue to 2 iterations in a "new process".
    cd2 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION,
                            validation_data=vdata,
                            validation_evaluators=ev)
    res = cd2.run(num_iterations=2, seed=5, checkpoint_dir=tmp_path)
    assert len(res.validation_history) == 2
    assert res.best_model is not None and res.best_metric is not None
    # Resumed run skipped iteration 1's updates: only iteration 2 re-ran.
    assert len(res.objective_history) == 4  # history restored + appended


def test_completed_run_resume_is_noop(rng, tmp_path):
    data, *_ = make_glmix_data(rng, n=200)
    cd1 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION)
    first = cd1.run(num_iterations=2, seed=3, checkpoint_dir=tmp_path)
    cd2 = CoordinateDescent(build_coordinates(data),
                            TaskType.LOGISTIC_REGRESSION)
    second = cd2.run(num_iterations=2, seed=3, checkpoint_dir=tmp_path)
    np.testing.assert_allclose(_final_coefs(second), _final_coefs(first),
                               rtol=1e-7)
    assert second.objective_history == first.objective_history


def test_estimator_checkpoint_plumbing(rng, tmp_path):
    from photon_ml_tpu.estimators.game_estimator import (
        FixedEffectSpec,
        GameEstimator,
    )
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
    )

    data, *_ = make_glmix_data(rng, n=200)
    spec = FixedEffectSpec(
        name="fixed", feature_shard_id="global",
        configs=[GLMOptimizationConfiguration(
            max_iterations=20, regularization_weight=1.0)])
    est = GameEstimator(task_type=TaskType.LOGISTIC_REGRESSION,
                        coordinate_specs=[spec], num_iterations=2)
    est.fit(data, checkpoint_dir=tmp_path)
    assert all_checkpoint_steps(tmp_path / "combo-0")
