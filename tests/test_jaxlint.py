"""jaxlint (photon_ml_tpu/analysis + dev_scripts/jaxlint.py): per-rule
true-positive AND false-positive fixtures, suppression + baseline
semantics, gate behavior on injected regressions, and a tree-clean run
over the actual repository.

Fixture sources carry device-path-looking relative paths
(photon_ml_tpu/ops/..., photon_ml_tpu/serving/...) because the host-sync
and dtype-drift rules scope themselves to device-path modules.
"""

from pathlib import Path

import pytest

from dev_scripts import jaxlint as cli
from photon_ml_tpu.analysis import (
    RULE_IDS,
    analyze_sources,
    apply_baseline,
    load_baseline,
    write_baseline,
)

REPO = Path(__file__).resolve().parents[1]

JIT_DEF = '''
import functools
import jax


@functools.partial(jax.jit, static_argnames=("fun", "max_iter"))
def solve(fun, x, max_iter=10):
    return fun(x)
'''


def rules_of(violations):
    return [v.rule for v in violations]


# -- retrace-hazard --------------------------------------------------------

def test_retrace_hazard_flags_lambda_in_static_kwarg():
    vs = analyze_sources({"photon_ml_tpu/optimization/s.py": JIT_DEF + '''

def caller(x):
    return solve(fun=lambda y: y + 1, x=x)
'''})
    assert rules_of(vs) == ["retrace-hazard"]
    assert "static arg 'fun'" in vs[0].message


def test_retrace_hazard_flags_local_def_in_static_position():
    vs = analyze_sources({"photon_ml_tpu/optimization/s.py": JIT_DEF + '''

def caller(x):
    def obj(y):
        return y * 2
    return solve(obj, x)
'''})
    assert rules_of(vs) == ["retrace-hazard"]
    assert "locally-defined function 'obj'" in vs[0].message


def test_retrace_hazard_flags_cross_module_call_site():
    vs = analyze_sources({
        "photon_ml_tpu/optimization/s.py": JIT_DEF,
        "photon_ml_tpu/algorithm/c.py": '''
from photon_ml_tpu.optimization.s import solve


def caller(x):
    return solve(fun=lambda y: y, x=x)
''',
    })
    assert rules_of(vs) == ["retrace-hazard"]
    assert vs[0].path == "photon_ml_tpu/algorithm/c.py"


def test_retrace_hazard_flags_per_call_jit():
    vs = analyze_sources({"photon_ml_tpu/ops/m.py": '''
import jax


def apply(f, x):
    return jax.jit(f)(x)


def loopy(f, xs):
    g = jax.jit(f)
    return [g(x) for x in xs]
'''})
    assert rules_of(vs) == ["retrace-hazard", "retrace-hazard"]


def test_retrace_hazard_accepts_stable_callables_and_cached_builders():
    """False positives the rule must NOT fire on: module-level functions
    and bound methods in static positions; jit results that are
    returned, stored in a cache, or built at module scope."""
    vs = analyze_sources({"photon_ml_tpu/optimization/s.py": JIT_DEF + '''

def objective(y):
    return y


def caller(x, model):
    solve(objective, x)
    return solve(fun=model.value, x=x)


def build(f):
    return jax.jit(f)  # builder: the CALLER owns caching


class Cache:
    def get(self, f, key):
        fn = jax.jit(f)
        self._entries[key] = fn
        return fn


TOP_LEVEL = jax.jit(lambda x: x)  # module scope: constructed once
'''})
    assert vs == []


# -- host-sync -------------------------------------------------------------

def test_host_sync_flags_syncs_inside_jitted_code():
    vs = analyze_sources({"photon_ml_tpu/ops/m.py": '''
import jax
import numpy as np


@jax.jit
def f(x, lo):
    a = x.sum().item()
    b = float(lo)
    c = np.asarray(x)
    x.block_until_ready()
    return a + b + c
'''})
    assert rules_of(vs) == ["host-sync"] * 4


def test_host_sync_sees_through_nested_and_traced_helpers():
    """Reachability: a lambda handed to lax.while_loop and a helper
    called from a jitted body are traced code too."""
    vs = analyze_sources({"photon_ml_tpu/ops/m.py": '''
import jax
from jax import lax


def helper(x, v):
    return x * float(v)


@jax.jit
def f(x, v, n):
    y = helper(x, v)
    return lax.while_loop(lambda c: c[1] < n,
                          lambda c: (c[0] + float(v), c[1] + 1), (y, 0))
'''})
    assert sorted(rules_of(vs)) == ["host-sync", "host-sync"]


def test_host_sync_ignores_host_code_statics_and_enums():
    """False positives: host-side functions may sync freely; float() of a
    declared static argname is trace-safe; int(Enum.X) is a python
    constant; non-device-path modules are out of scope."""
    vs = analyze_sources({
        "photon_ml_tpu/ops/m.py": '''
import functools
import jax


class Reason:
    OK = 1


def host_materialize(x):
    return float(x) + x.sum().item()


@functools.partial(jax.jit, static_argnames=("tol",))
def f(x, tol):
    t = float(tol)
    r = int(Reason.OK)
    return x * t + r
''',
        "photon_ml_tpu/io/m.py": '''
import jax


@jax.jit
def f(x, lo):
    return float(lo)
''',
    })
    assert vs == []


# -- dtype-drift -----------------------------------------------------------

def test_dtype_drift_flags_f64_and_dtypeless_float_literals():
    vs = analyze_sources({"photon_ml_tpu/serving/m.py": '''
import jax.numpy as jnp
import numpy as np


def g(n):
    a = jnp.zeros(n)
    b = jnp.array([1.0, 2.0])
    c = np.zeros(3, np.float64)
    return a, b, c
'''})
    assert rules_of(vs) == ["dtype-drift"] * 3


def test_dtype_drift_accepts_explicit_and_inherited_dtypes():
    vs = analyze_sources({"photon_ml_tpu/serving/m.py": '''
import jax.numpy as jnp


def g(n, x, dt):
    a = jnp.zeros(n, dt)
    b = jnp.zeros((), bool)
    c = jnp.array([1, 2])
    d = jnp.zeros_like(x)
    e = jnp.asarray(x)
    f = jnp.full((3,), 0.5, dt)
    g2 = jnp.ones(n, dtype=x.dtype)
    return a, b, c, d, e, f, g2
'''})
    assert vs == []


def test_dtype_drift_scoped_to_device_paths():
    vs = analyze_sources({"photon_ml_tpu/diagnostics/m.py": '''
import jax.numpy as jnp


def g(n):
    return jnp.zeros(n)
'''})
    assert vs == []


# -- nondeterministic-pytree -----------------------------------------------

def test_nondet_pytree_flags_set_iteration():
    vs = analyze_sources({"photon_ml_tpu/data/m.py": '''
def g(xs, t):
    leaves = [t[k] for k in {"a", "b"}]
    order = list(set(xs))
    for k in set(xs):
        leaves.append(k)
    return leaves, order
'''})
    assert rules_of(vs) == ["nondeterministic-pytree"] * 3


def test_nondet_pytree_accepts_sorted_sets_and_dicts():
    """sorted(set(...)) normalizes order; dicts preserve insertion
    order in python 3.7+ — neither may fire."""
    vs = analyze_sources({"photon_ml_tpu/data/m.py": '''
def g(xs, d):
    order = sorted(set(xs))
    keys = list(d)
    for k in d:
        order.append(k)
    for k in sorted({x + 1 for x in xs}):
        order.append(k)
    return order, keys
'''})
    assert vs == []


# -- suppression + fingerprints --------------------------------------------

def test_inline_suppression_silences_one_rule_on_one_line():
    src = '''
import jax


def apply(f, x):
    y = jax.jit(f)(x)  # jaxlint: disable=retrace-hazard
    return jax.jit(f)(y)
'''
    vs = analyze_sources({"photon_ml_tpu/ops/m.py": src})
    assert len(vs) == 1 and vs[0].line == 7  # only the unsuppressed line


def test_fingerprints_are_line_number_free():
    """Shifting a violation down the file must not change its
    fingerprint — baselines survive unrelated edits."""
    a = analyze_sources({"photon_ml_tpu/ops/m.py": '''
import jax


def apply(f, x):
    return jax.jit(f)(x)
'''})
    b = analyze_sources({"photon_ml_tpu/ops/m.py": '''
import jax

PAD = 1


def apply(f, x):
    return jax.jit(f)(x)
'''})
    assert a[0].line != b[0].line
    assert a[0].fingerprint == b[0].fingerprint


# -- baseline semantics ----------------------------------------------------

BAD_OPS = '''
import jax


def apply(f, x):
    return jax.jit(f)(x)
'''


def test_baseline_covers_and_uncovers(tmp_path):
    vs = analyze_sources({"photon_ml_tpu/ops/m.py": BAD_OPS})
    assert len(vs) == 1
    bl = tmp_path / "baseline.txt"
    write_baseline(bl, vs)
    new, stale = apply_baseline(vs, load_baseline(bl))
    assert new == [] and not stale
    # deleting the entry un-covers the violation
    new, _ = apply_baseline(vs, load_baseline(tmp_path / "missing.txt"))
    assert len(new) == 1
    # a baselined fingerprint occurring MORE often than accepted fails
    new, _ = apply_baseline(vs + vs, load_baseline(bl))
    assert len(new) == 1


def test_baseline_write_is_deterministic(tmp_path):
    vs = analyze_sources({
        "photon_ml_tpu/ops/b.py": BAD_OPS,
        "photon_ml_tpu/ops/a.py": BAD_OPS,
    })
    p1, p2 = tmp_path / "b1.txt", tmp_path / "b2.txt"
    write_baseline(p1, vs)
    write_baseline(p2, list(reversed(vs)))
    assert p1.read_text() == p2.read_text()
    body = [line for line in p1.read_text().splitlines()
            if line and not line.startswith("#")]
    assert body == sorted(body)
    assert all(line.startswith("photon_ml_tpu/ops/") for line in body)


# -- CLI gate --------------------------------------------------------------

CLEAN_MOD = '''
import jax


@jax.jit
def f(x):
    return x * 2
'''


def _write_tree(root: Path, files):
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)


def _gate(tmp_path, *extra):
    # --root scopes the default paths to the tmp tree (photon_ml_tpu/
    # exists there; absent defaults like bench.py are skipped).
    return cli.run(["--root", str(tmp_path),
                    "--baseline", str(tmp_path / "baseline.txt"), *extra])


def test_cli_clean_tree_exits_zero(tmp_path, capsys):
    _write_tree(tmp_path, {"photon_ml_tpu/ops/m.py": CLEAN_MOD})
    assert _gate(tmp_path) == 0
    assert "0 new" in capsys.readouterr().out


def test_cli_injected_per_call_jit_fails_gate(tmp_path):
    """Acceptance: injecting a per-call jax.jit into a fixture makes the
    gate fail."""
    _write_tree(tmp_path, {"photon_ml_tpu/ops/m.py": CLEAN_MOD})
    assert _gate(tmp_path) == 0
    _write_tree(tmp_path, {"photon_ml_tpu/ops/m.py": CLEAN_MOD + '''

def hot_path(g, x):
    return jax.jit(g)(x)
'''})
    assert _gate(tmp_path) == 1


def test_cli_baseline_update_then_delete_entry_fails_gate(tmp_path,
                                                          capsys):
    """Acceptance: --baseline-update regenerates deterministically and
    makes the gate pass; deleting any one baseline entry fails it."""
    _write_tree(tmp_path, {
        "photon_ml_tpu/ops/bad1.py": BAD_OPS,
        "photon_ml_tpu/serving/bad2.py": BAD_OPS,
    })
    assert _gate(tmp_path) == 1
    assert _gate(tmp_path, "--baseline-update") == 0
    first = (tmp_path / "baseline.txt").read_text()
    assert _gate(tmp_path, "--baseline-update") == 0
    assert (tmp_path / "baseline.txt").read_text() == first  # deterministic
    assert _gate(tmp_path) == 0
    lines = first.splitlines(keepends=True)
    entries = [i for i, line in enumerate(lines)
               if line.strip() and not line.startswith("#")]
    assert len(entries) == 2
    for drop in entries:  # deleting ANY one entry fails the gate
        (tmp_path / "baseline.txt").write_text(
            "".join(line for i, line in enumerate(lines) if i != drop))
        capsys.readouterr()
        assert _gate(tmp_path) == 1
        assert "1 new" in capsys.readouterr().out
    (tmp_path / "baseline.txt").write_text(first)
    assert _gate(tmp_path) == 0


def test_cli_stale_baseline_entry_noted_not_fatal(tmp_path, capsys):
    _write_tree(tmp_path, {"photon_ml_tpu/ops/bad1.py": BAD_OPS})
    assert _gate(tmp_path, "--baseline-update") == 0
    _write_tree(tmp_path, {"photon_ml_tpu/ops/bad1.py": CLEAN_MOD})  # fixed
    capsys.readouterr()
    assert _gate(tmp_path) == 0
    assert "stale" in capsys.readouterr().out


def test_cli_with_style_shares_the_walk(tmp_path, capsys):
    """--with-style folds dev_scripts/lint.py checks into the same run:
    a style problem fails the gate even when jaxlint itself is clean."""
    _write_tree(tmp_path, {"photon_ml_tpu/ops/m.py":
                           CLEAN_MOD + "x = 1  \n"})  # trailing whitespace
    capsys.readouterr()
    assert _gate(tmp_path, "--with-style") == 1
    out = capsys.readouterr().out
    assert "trailing whitespace" in out and "0 new" in out


def test_cli_baseline_update_refuses_path_subsets(tmp_path, capsys):
    """Scoped --baseline-update would silently drop accepted entries
    outside the subset — it must refuse."""
    _write_tree(tmp_path, {"photon_ml_tpu/ops/bad1.py": BAD_OPS})
    assert _gate(tmp_path, "--baseline-update",
                 str(tmp_path / "photon_ml_tpu")) == 2
    assert "must not be scoped" in capsys.readouterr().out
    assert not (tmp_path / "baseline.txt").exists()


def test_cli_errors_on_nonexistent_explicit_path(tmp_path):
    """A typo'd explicit path must error, not vacuously pass on 0
    files."""
    _write_tree(tmp_path, {"photon_ml_tpu/ops/m.py": CLEAN_MOD})
    with pytest.raises(SystemExit, match="path not found"):
        _gate(tmp_path, str(tmp_path / "photon_ml_typo"))


def test_cli_list_rules(capsys):
    assert cli.run(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in RULE_IDS:
        assert rule_id in out


def test_every_rule_has_an_id_and_doc():
    from photon_ml_tpu.analysis import ALL_RULES

    assert sorted(RULE_IDS) == sorted({
        "retrace-hazard", "host-sync", "dtype-drift",
        "nondeterministic-pytree", "telemetry-in-trace",
        "spill-dtype-leak", "blocking-in-async"})
    for rule in ALL_RULES:
        assert rule.doc and rule.id


# -- telemetry-in-trace ----------------------------------------------------

def test_telemetry_in_trace_flags_span_inside_jit():
    vs = analyze_sources({"photon_ml_tpu/ops/m.py": '''
import jax
from photon_ml_tpu.telemetry import span


@jax.jit
def f(x):
    with span("decode"):
        return x + 1
'''})
    assert rules_of(vs) == ["telemetry-in-trace"]
    assert "span" in vs[0].message


def test_telemetry_in_trace_flags_module_attr_and_mutation():
    """telemetry.histogram(...) opened in traced code + .inc()/.observe()
    mutations reached THROUGH a traced helper are all flagged."""
    vs = analyze_sources({"photon_ml_tpu/serving/m.py": '''
import jax
from photon_ml_tpu import telemetry

COUNTER = telemetry.counter("serving.rows")


def helper(x):
    COUNTER.inc()
    return x


@jax.jit
def f(x):
    h = telemetry.histogram("serving.lat")
    h.observe(0.1)
    return helper(x)
'''})
    assert sorted(rules_of(vs)) == ["telemetry-in-trace"] * 3


def test_telemetry_in_trace_ignores_host_loops_and_foreign_span():
    """False positives: instrumented HOST code (the adoption pattern —
    span around the dispatch loop) is fine, and an unrelated local
    function named `span` is not the telemetry one."""
    vs = analyze_sources({"photon_ml_tpu/ops/m.py": '''
import jax
from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import span

_H = telemetry.histogram("training.iteration_seconds")


@jax.jit
def kernel(x):
    return x * 2


def host_loop(xs):
    out = []
    with span("accumulate"):
        for x in xs:
            out.append(kernel(x))
    _H.observe(1.0)
    return out
''',
        "photon_ml_tpu/serving/n.py": '''
import jax


def span(n):
    return n


@jax.jit
def f(x):
    return x + span(1)
'''})
    assert vs == []


# -- spill-dtype-leak ------------------------------------------------------

def test_spill_dtype_leak_flags_encoded_buffers_outside_restore():
    """True positives: spill-ENCODED buffers (bf16 values, delta-coded
    indices) consumed anywhere but the shard cache's blessed restore
    path — here leaking straight into a device-kernel feature build."""
    vs = analyze_sources({"photon_ml_tpu/ops/bad.py": '''
import jax.numpy as jnp


def accumulate(e, n_features):
    values = jnp.asarray(e.spill.enc_values)
    cols = jnp.asarray(e.spill.enc_cols)
    return values, cols
''',
        "photon_ml_tpu/data/other.py": '''

def peek(spill):
    return spill.enc_rows[:4]
'''})
    assert rules_of(vs) == ["spill-dtype-leak"] * 3
    assert "restore_spilled_features" in vs[0].message


def test_spill_dtype_leak_allows_codec_and_foreign_paths():
    """False positives: the codec pair + SpillBlock.nbytes in
    data/shard_cache.py are the blessed consumers; code outside
    photon_ml_tpu/ (tests, bench) pokes the fields legitimately;
    non-encoded attributes never trip the rule."""
    vs = analyze_sources({"photon_ml_tpu/data/shard_cache.py": '''
import numpy as np


class SpillBlock:
    @property
    def nbytes(self):
        return self.enc_values.nbytes + self.enc_cols.nbytes


def encode_spill(values, nnz):
    out = SpillBlock()
    return out.enc_values


def restore_spilled_features(spill):
    return np.asarray(spill.enc_values), np.asarray(spill.enc_rows)


def other_fn(spill):
    return spill.dtype_tag  # not an encoded buffer
''',
        "tests/test_codec.py": '''

def test_roundtrip(blk):
    assert blk.enc_values.dtype.itemsize == 2
'''})
    assert vs == []


def test_spill_dtype_leak_flags_leak_even_inside_shard_cache():
    """A NON-blessed function inside shard_cache itself must still be
    flagged (the allowance is function-scoped, not module-wide)."""
    vs = analyze_sources({"photon_ml_tpu/data/shard_cache.py": '''

def ensure(e):
    return e.spill.enc_values  # bypasses restore_spilled_features
'''})
    assert rules_of(vs) == ["spill-dtype-leak"]


# -- blocking-in-async -----------------------------------------------------

def test_blocking_in_async_flags_sleep_sync_get_and_block():
    vs = analyze_sources({"photon_ml_tpu/serving/f.py": '''
import queue
import time

q = queue.Queue()


async def batcher(x):
    time.sleep(0.002)
    item = q.get()
    x.block_until_ready()
    return item
'''})
    assert rules_of(vs) == ["blocking-in-async"] * 3
    assert "event loop" in vs[0].message
    assert "asyncio.Queue" in vs[1].message
    assert "run_in_executor" in vs[2].message


def test_blocking_in_async_flags_from_import_sleep():
    """'from time import sleep' is the same blocking call under a bare
    name — the attribute-form match alone must not be bypassable."""
    vs = analyze_sources({"photon_ml_tpu/serving/f.py": '''
from time import sleep


async def batcher():
    sleep(0.002)
'''})
    assert rules_of(vs) == ["blocking-in-async"]
    # ...while a local function that HAPPENS to be called sleep is fine
    vs = analyze_sources({"photon_ml_tpu/serving/f.py": '''
def sleep(dt):
    return dt


async def batcher():
    sleep(0.002)
'''})
    assert vs == []


def test_blocking_in_async_accepts_awaits_timeouts_and_sync_defs():
    """await asyncio.sleep / awaited queue gets / timeout= handoffs are
    the correct patterns; sync defs (executor-thread bodies) and
    dict.get(key) must not trip the rule."""
    vs = analyze_sources({"photon_ml_tpu/serving/f.py": '''
import asyncio
import queue
import time

q = queue.Queue()
aq = asyncio.Queue()


async def batcher(cfg):
    await asyncio.sleep(0.002)
    item = await aq.get()
    handoff = q.get(timeout=1.0)
    window = cfg.get("window", 0.002)  # dict lookup, not a queue
    return item, handoff, window


def executor_body(x):
    time.sleep(0.002)  # sync def: runs on a worker thread, may block
    return q.get()
'''})
    assert vs == []


def test_blocking_in_async_executor_lambda_is_exempt():
    """The rule's own recommended remediation — a blocking body handed
    to run_in_executor/submit — must not be flagged; a lambda merely
    DEFINED in the coroutine (called inline) still is."""
    vs = analyze_sources({"photon_ml_tpu/serving/f.py": '''
import asyncio


async def dispatch(loop, pool, out):
    await loop.run_in_executor(None, lambda: out.block_until_ready())
    pool.submit(lambda: out.block_until_ready())
'''})
    assert vs == []
    vs = analyze_sources({"photon_ml_tpu/serving/f.py": '''
async def dispatch(out):
    wait = lambda: out.block_until_ready()
    return wait()
'''})
    assert rules_of(vs) == ["blocking-in-async"]


def test_blocking_in_async_scoped_to_serving():
    src = '''
import time


async def poll():
    time.sleep(0.01)
'''
    assert rules_of(analyze_sources(
        {"photon_ml_tpu/serving/f.py": src})) == ["blocking-in-async"]
    # outside serving/ there is no event-loop contract to protect
    assert analyze_sources({"photon_ml_tpu/data/f.py": src}) == []


def test_blocking_in_async_covers_net_cli_modules():
    """The network front door grew event loops outside serving/: the
    router CLI and the scoring driver's --listen mode are covered
    file-wise (rule _FILES), while other cli/ modules stay exempt."""
    src = '''
import time


async def poll():
    time.sleep(0.01)
'''
    for covered in ("photon_ml_tpu/cli/net_router.py",
                    "photon_ml_tpu/cli/game_scoring_driver.py"):
        assert rules_of(analyze_sources(
            {covered: src})) == ["blocking-in-async"], covered
    # a different cli module (no event loop of its own) is not scoped
    assert analyze_sources(
        {"photon_ml_tpu/cli/game_training_driver.py": src}) == []


# -- the actual tree is clean ----------------------------------------------

def test_repo_tree_is_jaxlint_clean(capsys):
    """Acceptance: `python dev_scripts/jaxlint.py` exits 0 on the tree
    (no NEW violations against the checked-in baseline)."""
    assert cli.run([]) == 0
    assert "0 new" in capsys.readouterr().out
