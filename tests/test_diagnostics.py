"""Diagnostics subsystem tests (reference test strategy: SURVEY.md §4 —
statistics checked against closed forms / scipy; driver wiring checked
end-to-end on tiny synthetic data)."""

import json
import re

import numpy as np
import pytest
import scipy.stats

from photon_ml_tpu.data.stats import BasicStatisticalSummary
from photon_ml_tpu.diagnostics import (
    DiagnosticReport,
    bootstrap_training,
    expected_magnitude_importance,
    fitting_diagnostic,
    hosmer_lemeshow_diagnostic,
    kendall_tau_analysis,
    prediction_error_independence,
    render_html_report,
    variance_importance,
)
from photon_ml_tpu.diagnostics.reporting import (
    DiagnosticMode,
    ModelDiagnosticReport,
)


def test_kendall_tau_matches_scipy(rng):
    a = rng.normal(size=200)
    b = 0.5 * a + rng.normal(size=200)
    report = kendall_tau_analysis(a, b)
    expected = scipy.stats.kendalltau(a, b, variant="b").statistic
    assert report.tau_beta == pytest.approx(expected, abs=1e-12)
    assert report.num_pairs == 200 * 199 // 2
    assert report.num_concordant + report.num_discordant == \
        report.effective_pairs


def test_kendall_tau_independent_vs_dependent(rng):
    a = rng.normal(size=500)
    independent = kendall_tau_analysis(a, rng.normal(size=500))
    dependent = kendall_tau_analysis(a, a + 0.01 * rng.normal(size=500))
    assert abs(independent.tau_alpha) < 0.1
    assert dependent.tau_alpha > 0.9
    # Two-sided p-value: tiny under strong dependence, large-ish when
    # independent; the reference's P(|Z|<=z) is kept as `confidence`.
    assert dependent.p_value < 1e-6
    assert dependent.confidence > 0.99
    assert independent.p_value > 0.01


def test_kendall_tau_tie_accounting():
    report = kendall_tau_analysis([1.0, 1.0, 2.0], [1.0, 2.0, 3.0])
    # Pair (0,1) ties in a; pairs (0,2) and (1,2) are concordant.
    assert report.num_concordant == 2
    assert report.num_discordant == 0
    assert "ties" in report.message.lower()


def test_prediction_error_independence_samples_capped(rng):
    labels = rng.normal(size=8000)
    preds = labels + rng.normal(size=8000)
    report = prediction_error_independence(labels, preds)
    assert len(report.predictions) == 5000
    assert report.kendall_tau.num_items == 5000


def test_hosmer_lemeshow_calibrated_vs_miscalibrated(rng):
    n = 4000
    p = rng.uniform(0.05, 0.95, n)
    y = (rng.random(n) < p).astype(float)
    good = hosmer_lemeshow_diagnostic(y, p, num_dimensions=8)
    bad = hosmer_lemeshow_diagnostic(y, np.clip(p * 0.4, 0, 1),
                                     num_dimensions=8)
    assert bad.chi_square > good.chi_square
    assert good.degrees_of_freedom == len(good.bins) - 2
    # All rows land in exactly one bin.
    assert sum(b.total for b in good.bins) == n
    # Midpoint-based expected counts conserve totals.
    for b in good.bins:
        assert b.expected_pos + b.expected_neg == b.total
    d = good.to_dict()
    assert d["pValue"] == pytest.approx(1.0 - d["probAtChiSquare"])


def test_feature_importance_ranking(rng):
    x = rng.normal(0, 1, (500, 4))
    x[:, 2] *= 10.0  # large spread -> large meanAbs and variance
    summary = BasicStatisticalSummary.compute(x)
    coef = np.array([0.1, 0.1, 0.1, 0.1])
    names = ["a", "b", "big", "d"]

    em = expected_magnitude_importance(coef, summary, names)
    assert em.ranked_features[0][0] == "big"
    var = variance_importance(coef, summary, names)
    assert var.ranked_features[0][0] == "big"
    # Without a summary both collapse to |coef|.
    em_plain = expected_magnitude_importance(np.array([1.0, -3.0]), None,
                                             ["u", "v"])
    assert em_plain.ranked_features[0][0] == "v"
    assert em_plain.ranked_features[0][2] == pytest.approx(3.0)


def _toy_trainer(x, y, lam_grid):
    """Closed-form ridge per λ — a stand-in for train_glm_models."""

    class Model:
        def __init__(self, means):
            self.coefficients = type("C", (), {"means": means})()

    def train(train_idx, holdout_idx, warm):
        out = []
        for lam in lam_grid:
            xt, yt = x[train_idx], y[train_idx]
            w = np.linalg.solve(xt.T @ xt + lam * np.eye(x.shape[1]),
                                xt.T @ yt)
            def mse(idx):
                r = x[idx] @ w - y[idx]
                return {"MSE": float(r @ r / max(len(idx), 1))}
            out.append((lam, Model(w), mse(train_idx), mse(holdout_idx)))
        return out

    return train


def test_fitting_diagnostic_learning_curves(rng):
    n, d = 2000, 3
    x = rng.normal(size=(n, d))
    y = x @ np.array([1.0, -2.0, 0.5]) + 0.1 * rng.normal(size=n)
    reports = fitting_diagnostic(n, d, _toy_trainer(x, y, [1.0]))
    assert set(reports) == {1.0}
    portions, train, holdout = reports[1.0].metrics["MSE"]
    assert len(portions) == 9  # fractions 10%..90%
    assert portions == sorted(portions)
    # More data shrinks the generalization gap.
    assert abs(holdout[-1] - train[-1]) <= abs(holdout[0] - train[0]) + 0.05


def test_fitting_diagnostic_too_small_returns_empty(rng):
    assert fitting_diagnostic(50, 10, _toy_trainer(
        rng.normal(size=(50, 10)), rng.normal(size=50), [1.0])) == {}


def test_bootstrap_confidence_intervals(rng):
    n, d = 1200, 3
    true_w = np.array([2.0, -1.0, 0.0])
    x = rng.normal(size=(n, d))
    y = x @ true_w + 0.5 * rng.normal(size=n)
    trainer = _toy_trainer(x, y, [0.1])

    def bs_trainer(train_idx, holdout_idx, warm):
        return [(lam, m, hold)
                for lam, m, _, hold in trainer(train_idx, holdout_idx, warm)]

    reports = bootstrap_training(n, bs_trainer, num_bootstrap_samples=5,
                                 population_portion=0.8)
    rep = reports[0.1]
    assert rep.num_models == 5
    cis = rep.coefficient_intervals
    assert len(cis) == d
    for j in range(d):
        assert cis[j].min <= true_w[j] + 0.2
        assert cis[j].max >= true_w[j] - 0.2
        assert cis[j].count == 5
    assert "MSE" in rep.metric_intervals
    assert rep.metric_intervals["MSE"].mean < 1.0


def test_bootstrap_validates_args():
    with pytest.raises(ValueError):
        bootstrap_training(10, lambda *a: [], num_bootstrap_samples=1)
    with pytest.raises(ValueError):
        bootstrap_training(10, lambda *a: [], num_bootstrap_samples=2,
                           population_portion=1.5)


def test_coefficient_summary_welford():
    from photon_ml_tpu.diagnostics import CoefficientSummary

    s = CoefficientSummary()
    values = [1.0, 2.0, 3.0, 4.0]
    for v in values:
        s.accumulate(v)
    assert s.mean == pytest.approx(np.mean(values))
    assert s.variance == pytest.approx(np.var(values, ddof=1))
    assert (s.min, s.max) == (1.0, 4.0)


def test_render_html_report_smoke():
    report = DiagnosticReport(
        system={"task": "LOGISTIC_REGRESSION", "numRows": 10},
        models=[ModelDiagnosticReport(
            model_description="LogisticRegressionModel", reg_weight=1.0,
            metrics={"AUC": 0.9})])
    page = render_html_report(report)
    assert "LogisticRegressionModel" in page and "AUC" in page
    assert DiagnosticMode("ALL").train_enabled
    assert not DiagnosticMode("VALIDATE").train_enabled


@pytest.mark.slow
def test_glm_driver_diagnostic_mode(tmp_path, rng):
    from tests.test_cli_drivers import _write_glm_avro
    from photon_ml_tpu.cli.glm_driver import run

    train, valid, out = (tmp_path / "t", tmp_path / "v", tmp_path / "o")
    w_true = np.array([1.0, -1.0, 0.5, 0.0, 2.0])
    _write_glm_avro(train, rng, n=400, w=w_true)
    _write_glm_avro(valid, rng, n=150, w=w_true)
    run(["--training-data-directory", str(train),
         "--validating-data-directory", str(valid),
         "--output-directory", str(out),
         "--task", "LOGISTIC_REGRESSION",
         "--regularization-weights", "1.0",
         "--max-num-iterations", "30",
         "--diagnostic-mode", "ALL",
         "--num-bootstrap-samples", "2"])
    doc = json.loads((out / "model-diagnostic.json").read_text())
    assert doc["system"]["diagnosticMode"] == "ALL"
    (model,) = doc["models"]
    assert model["featureImportance"][0]["rankedFeatures"]
    assert "hosmerLemeshow" in model
    assert "predictionErrorIndependence" in model
    assert "fitting" in model and "bootstrap" in model
    assert (out / "model-diagnostic.html").exists()
    # Every table the reference renders as an xchart plot
    # (ml/diagnostics/reporting/html/) gets an inline-SVG chart: feature
    # importance, learning curves, bootstrap CIs, HL calibration.
    report_html = (out / "model-diagnostic.html").read_text()
    assert report_html.count("<svg") >= 4, report_html.count("<svg")
    import xml.etree.ElementTree as ET

    for svg in re.findall(r"<svg.*?</svg>", report_html, re.S):
        ET.fromstring(svg)  # well-formed
    summary = json.loads((out / "summary.json").read_text())
    assert "DIAGNOSED" in summary["stages"]
