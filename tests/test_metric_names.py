"""dev_scripts/metric_names.py (the metric-name schema gate): one
true-positive and one false-positive case per rule, the conflicting-type
check, partial-literal fragment handling, and a tree-clean run over the
repository — the same guarded-gate discipline as test_lint.py."""

from pathlib import Path

from dev_scripts import metric_names

REPO = Path(__file__).resolve().parents[1]


def violations(tmp_path, src, name="m.py"):
    p = tmp_path / name
    p.write_text(src)
    regs: dict = {}
    out = metric_names.check_file(p, src, regs)
    out.extend(metric_names.conflicting_types(regs))
    return [(rule, msg) for _, _, rule, msg in out]


TELEM = "from photon_ml_tpu.telemetry import counter, gauge, histogram\n"


def test_snake_case_dotted_names_pass(tmp_path):
    src = (TELEM +
           'counter("serving.frontend.admitted")\n'
           'gauge("data.shard_cache.device_bytes")\n'
           'histogram("p99.latency_2x", buckets=[1.0])\n')
    assert violations(tmp_path, src) == []


def test_camel_case_flagged(tmp_path):
    out = violations(tmp_path, TELEM + 'counter("serving.numRows")\n')
    assert len(out) == 1 and out[0][0] == "metric-name-schema"


def test_bad_shapes_flagged(tmp_path):
    for bad in ('counter("has-hyphen.x")', 'counter("has space")',
                'counter(".leading.dot")', 'counter("trailing.dot.")',
                'counter("double..dot")', 'counter("9starts.digit")'):
        out = violations(tmp_path, TELEM + bad + "\n")
        assert out and out[0][0] == "metric-name-schema", bad


def test_attribute_form_checked_bare_foreign_name_exempt(tmp_path):
    # telemetry.counter(...) attribute form is checked with no import
    out = violations(
        tmp_path, "from photon_ml_tpu import telemetry\n"
                  'telemetry.counter("BadName")\n')
    assert len(out) == 1
    # a foreign local function that happens to be called counter() is
    # NOT a telemetry registration
    assert violations(
        tmp_path, "def counter(x):\n    return x\n"
                  'counter("Whatever CamelCase")\n') == []


def test_conflicting_type_registration_flagged(tmp_path):
    src = (TELEM +
           'counter("stream.rows")\n'
           'gauge("stream.rows")\n')
    out = violations(tmp_path, src)
    assert any(rule == "metric-type-conflict" for rule, _ in out)
    # same name, same type, several sites: fine (get-or-create contract)
    ok = TELEM + 'counter("stream.rows")\ncounter("stream.rows")\n'
    assert violations(tmp_path, ok) == []


def test_partial_literals_fragments_checked(tmp_path):
    # constant-concat chains are schema-checked WHOLE
    ok = TELEM + 'counter("serving.model." + "requests")\n'
    assert violations(tmp_path, ok) == []
    bad = TELEM + 'counter("serving.model." + "numRows")\n'
    assert violations(tmp_path, bad)
    # dynamic parts pass, but bad literal FRAGMENTS are caught
    ok_dyn = (TELEM +
              'counter(f"serving.model.{label}.rejected")\n'
              'counter(prefix + "rejected")\n')
    assert violations(tmp_path, ok_dyn) == []
    bad_dyn = TELEM + 'counter(f"serving.model.{label}.numRows")\n'
    out = violations(tmp_path, bad_dyn)
    assert out and "fragment" in out[0][1]


def test_fully_dynamic_name_is_runtime_problem(tmp_path):
    assert violations(tmp_path, TELEM + "counter(name_var)\n") == []


def test_exemplar_histogram_must_name_seconds(tmp_path):
    # TP: exemplar-bearing histogram without a _seconds suffix
    bad = TELEM + 'histogram("serving.request_count", exemplars=True)\n'
    out = violations(tmp_path, bad)
    assert len(out) == 1 and out[0][0] == "exemplar-histogram-name"
    # FP guards: _seconds-suffixed declaration, explicit False, and a
    # plain histogram are all clean
    ok = (TELEM +
          'histogram("serving.latency_seconds", exemplars=True)\n'
          'histogram("serving.group_rows", exemplars=False)\n'
          'histogram("serving.other_rows")\n')
    assert violations(tmp_path, ok) == []


def test_exemplar_declaration_conflict_flagged(tmp_path):
    # explicit True at one site + explicit False at another: conflict
    src = (TELEM +
           'histogram("serving.latency_seconds", exemplars=True)\n'
           'histogram("serving.latency_seconds", exemplars=False)\n')
    out = violations(tmp_path, src)
    assert any(rule == "exemplar-declaration-conflict"
               for rule, _ in out)
    assert not any(rule == "metric-type-conflict" for rule, _ in out)
    # a kwarg-less READ of the same name (bench snapshots do this) is
    # NOT a conflicting declaration
    ok = (TELEM +
          'histogram("serving.latency_seconds", exemplars=True)\n'
          'histogram("serving.latency_seconds")\n')
    assert violations(tmp_path, ok) == []


def test_gauge_only_dist_family(tmp_path):
    # TP: data.dist.* as counter / histogram is flagged
    for bad in ('counter("data.dist.rows")',
                'histogram("data.dist.label_p50")'):
        out = violations(tmp_path, TELEM + bad + "\n")
        assert any(rule == "gauge-only-family" for rule, _ in out), bad
    # the f-string form of the family is caught too (prefix-anchored
    # on the leading fragment)
    out = violations(tmp_path,
                     TELEM + 'counter(f"data.dist.{col}_p50")\n')
    assert any(rule == "gauge-only-family" for rule, _ in out)
    # FP guards: gauges in the family are the contract; neighboring
    # non-family names keep their kinds; a fragment merely CONTAINING
    # the prefix mid-name is a different namespace
    ok = (TELEM +
          'gauge("data.dist.rows")\n'
          'gauge("data.dist.label_p99")\n'
          'counter("data.shard_cache.hits")\n'
          'counter("data.distance_unrelated")\n'
          'counter(f"{ns}.metadata.dist.errors")\n')
    assert violations(tmp_path, ok) == []


def test_gauge_only_drift_family_fragments(tmp_path):
    # TP: the per-model f-string form — literal FRAGMENTS carry the
    # score_drift_ marker even though the label is dynamic
    bad = TELEM + 'counter(f"serving.model.{label}.score_drift_psi")\n'
    out = violations(tmp_path, bad)
    assert any(rule == "gauge-only-family" for rule, _ in out)
    # full-literal drift names as non-gauges are flagged too
    out = violations(
        tmp_path,
        TELEM + 'histogram("serving.model.a.score_drift_ks")\n')
    assert any(rule == "gauge-only-family" for rule, _ in out)
    # FP guards: drift gauges (literal and f-string) are clean
    ok = (TELEM +
          'gauge(f"serving.model.{label}.score_drift_psi")\n'
          'gauge("serving.model.a.score_drift_ks")\n'
          'counter(f"serving.model.{label}.rejected")\n')
    assert violations(tmp_path, ok) == []


def test_repo_tree_is_clean():
    assert metric_names.main(["--root", str(REPO)]) == 0


# -- fleet.* reservation + gauge merge policies (PR: federation) -----------

def _violations(tmp_path, src, name="m.py", policies=None):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src)
    out = metric_names.check_file(p, src, {}, gauge_policies=policies)
    return [(rule, msg) for _, _, rule, msg in out]


def test_fleet_prefix_reserved(tmp_path):
    # TP: an ordinary module registering a fleet.* name (full literal
    # and literal fragment of an f-string) collides with the merged
    # plane's synthesized series
    out = _violations(tmp_path, TELEM + 'gauge("fleet.peers")\n')
    assert any(rule == "fleet-prefix-reserved" for rule, _ in out)
    out = _violations(tmp_path,
                      TELEM + 'gauge(f"fleet.peer.{pid}.stale")\n')
    assert any(rule == "fleet-prefix-reserved" for rule, _ in out)
    # FP guards: federation.py itself owns the prefix; a name merely
    # CONTAINING "fleet." mid-name is a different namespace
    out = _violations(tmp_path, TELEM + 'gauge("fleet.peers")\n',
                      name="telemetry/federation.py")
    assert not any(rule == "fleet-prefix-reserved" for rule, _ in out)
    assert _violations(tmp_path,
                       TELEM + 'counter("my.fleet.rows")\n') == []


_POL = {"data.dist.rows": "sum", ".burn_rate": "max",
        "data.dist.": "last"}


def test_gauge_merge_policy_required(tmp_path):
    # TP: a gauge family with no declared policy entry (full literal
    # and a partially-dynamic name with no covered fragment)
    out = _violations(tmp_path, TELEM + 'gauge("new.thing_bytes")\n',
                      policies=_POL)
    assert any(rule == "gauge-merge-policy" for rule, _ in out)
    out = _violations(tmp_path, TELEM + 'gauge(f"new.{x}.thing")\n',
                      policies=_POL)
    assert any(rule == "gauge-merge-policy" for rule, _ in out)
    # FP guards: exact, .suffix, prefix., fragment-prefix, and a
    # concatenated fragment carrying the suffix without its dot
    ok = (TELEM +
          'gauge("data.dist.rows")\n'
          'gauge("slo.x.burn_rate")\n'
          'gauge("data.dist.label_mean")\n'
          'gauge(f"data.dist.{col}_mean")\n'
          'gauge(pre + "burn_rate")\n')
    assert _violations(tmp_path, ok, policies=_POL) == []
    # counters/histograms need no policy; rule skipped when the tree
    # has no federation table (policies=None)
    assert _violations(tmp_path, TELEM + 'counter("new.thing")\n',
                       policies=_POL) == []
    assert _violations(tmp_path, TELEM + 'gauge("new.thing")\n') == []


def test_load_gauge_policies(tmp_path):
    # absent module -> None (rule skipped entirely)
    assert metric_names.load_gauge_policies(tmp_path) is None
    # a tmp tree can declare its own minimal table
    fedp = tmp_path / "photon_ml_tpu" / "telemetry"
    fedp.mkdir(parents=True)
    (fedp / "federation.py").write_text(
        'GAUGE_MERGE_POLICIES = {"a.b.": "sum", ".c": "max"}\n')
    assert metric_names.load_gauge_policies(tmp_path) == {
        "a.b.": "sum", ".c": "max"}
    # the real tree's table parses and holds only valid policies
    real = metric_names.load_gauge_policies(REPO)
    assert real and set(real.values()) <= {"sum", "max", "last"}


def test_gauge_policy_rule_wired_through_main(tmp_path):
    fedp = tmp_path / "photon_ml_tpu" / "telemetry"
    fedp.mkdir(parents=True)
    (fedp / "federation.py").write_text(
        'GAUGE_MERGE_POLICIES = {"covered.": "sum"}\n')
    (tmp_path / "bench.py").write_text("")
    mod = tmp_path / "photon_ml_tpu" / "mod.py"
    mod.write_text(TELEM + 'gauge("uncovered.bytes")\n')
    assert metric_names.main(["--root", str(tmp_path)]) == 1
    mod.write_text(TELEM + 'gauge("covered.bytes")\n')
    assert metric_names.main(["--root", str(tmp_path)]) == 0


# -- serving.net.* counter family (PR: network front door) -----------------

def test_counter_family_serving_net(tmp_path):
    # TP: gauges (outside the allowlist) and histograms under the
    # serving.net. prefix break the wire-event family — dashboards
    # rate() the whole namespace
    for bad in ('gauge("serving.net.bytes_read")',
                'histogram("serving.net.request_latency_seconds")',
                'histogram("serving.net.frame_bytes")'):
        out = violations(tmp_path, TELEM + bad + "\n")
        assert any(rule == "counter-family" for rule, _ in out), bad
    # the f-string form is caught too (prefix-anchored on the leading
    # fragment, like the gauge-only prefix families)
    out = violations(tmp_path,
                     TELEM + 'gauge(f"serving.net.peer.{pid}.lag")\n')
    assert any(rule == "counter-family" for rule, _ in out)


def test_counter_family_fp_guards(tmp_path):
    # counters throughout the family are the contract; the allowlisted
    # open_connections gauge is the one sanctioned instantaneous
    # reading; neighboring namespaces keep their kinds; a name merely
    # CONTAINING the prefix mid-name is a different namespace
    ok = (TELEM +
          'counter("serving.net.wire_errors")\n'
          'counter(f"serving.net.errors.{kind}")\n'
          'counter("serving.net.bytes_written")\n'
          'gauge("serving.net.open_connections")\n'
          'gauge("serving.adaptive.burn_rate")\n'
          'histogram("serving.frontend.request_latency_seconds")\n'
          'counter(f"{ns}.serving.net.shadow")\n')
    assert violations(tmp_path, ok) == []
