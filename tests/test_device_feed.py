"""Direct tests for data/device_feed.py HostPrefetcher shutdown paths
(previously exercised only indirectly through test_block_stream.py):
consumer close() mid-stream, producer exceptions after partial
consumption, and generator abandonment must all stop the producer thread
promptly instead of leaving it blocked on a full queue forever.
"""

import gc
import threading
import time

import pytest

from photon_ml_tpu.data.device_feed import HostPrefetcher


class _CountingSource:
    """Unbounded source that records how far production got."""

    def __init__(self, n=10**9, delay=0.0):
        self.produced = 0
        self.n = n
        self.delay = delay
        self.exited = threading.Event()

    def __iter__(self):
        try:
            for i in range(self.n):
                if self.delay:
                    time.sleep(self.delay)
                self.produced += 1
                yield i
        finally:
            self.exited.set()


def _assert_stops(src, timeout=3.0):
    """Producer must halt: `produced` stabilizes well below the source
    length within the poll-stop window."""
    deadline = time.monotonic() + timeout
    last = -1
    while time.monotonic() < deadline:
        now = src.produced
        if now == last:
            return now
        last = now
        time.sleep(3 * HostPrefetcher._POLL_S)
    raise AssertionError(f"producer still running: produced={src.produced}")


def test_close_mid_stream_stops_producer():
    src = _CountingSource()
    pf = HostPrefetcher(src, depth=2)
    it = iter(pf)
    assert next(it) == 0
    assert next(it) == 1
    it.close()  # consumer walks away mid-stream
    final = _assert_stops(src)
    # Bounded overrun: queue depth + producer's hand, not the whole
    # source (the poll-stop flag is checked on every blocked put).
    assert final <= 2 + 2 + 2


def test_generator_abandonment_stops_producer():
    src = _CountingSource()
    it = iter(HostPrefetcher(src, depth=1))
    assert next(it) == 0
    del it  # GC finalizes the generator -> finally -> stop flag
    gc.collect()
    final = _assert_stops(src)
    assert final <= 1 + 2 + 2


def test_producer_exception_reraised_at_position():
    def src():
        yield 1
        yield 2
        raise ValueError("decode exploded at block 2")

    it = iter(HostPrefetcher(src(), depth=2))
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(ValueError, match="decode exploded at block 2"):
        next(it)


def test_producer_exception_before_any_item():
    def src():
        raise RuntimeError("corrupt header")
        yield  # pragma: no cover

    with pytest.raises(RuntimeError, match="corrupt header"):
        next(iter(HostPrefetcher(src(), depth=2)))


def test_exhaustion_is_clean_and_ordered():
    src = _CountingSource(n=7)
    assert list(HostPrefetcher(src, depth=3)) == list(range(7))
    assert src.exited.wait(2.0)


def test_close_then_new_iteration_is_fresh():
    """Each __iter__ spins an independent producer; closing one must not
    poison the next."""
    src1 = _CountingSource(n=5)
    pf = HostPrefetcher(src1, depth=1)
    it = iter(pf)
    next(it)
    it.close()
    _assert_stops(src1)
    pf2 = HostPrefetcher(_CountingSource(n=4), depth=1)
    assert list(pf2) == [0, 1, 2, 3]
