"""Config parsing parity tests (reference:
GLMOptimizationConfigurationTest, RegularizationContextTest)."""

import pytest

from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)


def test_parse_six_field_string():
    c = GLMOptimizationConfiguration.parse("10,1e-5,0.3,0.5,TRON,L2")
    assert c.max_iterations == 10
    assert c.tolerance == 1e-5
    assert c.regularization_weight == 0.3
    assert c.down_sampling_rate == 0.5
    assert c.optimizer_type == OptimizerType.TRON
    assert c.regularization_context.reg_type == RegularizationType.L2


def test_parse_elastic_net_with_alpha():
    c = GLMOptimizationConfiguration.parse("50,1e-6,1.0,1.0,LBFGS,ELASTIC_NET,0.4")
    rc = c.regularization_context
    assert rc.reg_type == RegularizationType.ELASTIC_NET
    assert rc.l1_weight(10.0) == pytest.approx(4.0)
    assert rc.l2_weight(10.0) == pytest.approx(6.0)


def test_round_trip_string_and_json():
    for s in ["10,1e-5,0.3,0.5,TRON,L2", "50,1e-06,1.0,1.0,LBFGS,ELASTIC_NET,0.4"]:
        c = GLMOptimizationConfiguration.parse(s)
        assert GLMOptimizationConfiguration.parse(c.to_string()) == c
        assert GLMOptimizationConfiguration.from_json(c.to_json()) == c


@pytest.mark.parametrize("bad", [
    "10,1e-5,0.3,0.5,TRON",  # five fields
    "10,1e-5,0.3,1.5,TRON,L2",  # sampling rate > 1
    "10,1e-5,-0.3,0.5,TRON,L2",  # negative reg weight
    "0,1e-5,0.3,0.5,TRON,L2",  # zero iterations
    "10,1e-5,0.3,0.5,ADAM,L2",  # unknown optimizer
])
def test_parse_rejects_bad_strings(bad):
    with pytest.raises(ValueError):
        GLMOptimizationConfiguration.parse(bad)


def test_regularization_context_validation():
    with pytest.raises(ValueError):
        RegularizationContext(RegularizationType.ELASTIC_NET, None)
    with pytest.raises(ValueError):
        RegularizationContext(RegularizationType.ELASTIC_NET, 1.5)
    with pytest.raises(ValueError):
        RegularizationContext(RegularizationType.L2, 0.5)
    rc = RegularizationContext(RegularizationType.L1)
    assert rc.l1_weight(3.0) == 3.0 and rc.l2_weight(3.0) == 0.0


def test_optimizer_config_defaults():
    assert OptimizerConfig(OptimizerType.LBFGS).resolved().max_iterations == 100
    assert OptimizerConfig(OptimizerType.TRON).resolved().tolerance == 1e-5
    c = OptimizerConfig(OptimizerType.TRON, 7, 1e-3, {2: (0.0, 1.0)})
    assert OptimizerConfig.from_json(c.to_json()) == c
