"""Golden-value parity against the reference's OWN integration fixtures.

The reference encodes its expected behavior in
photon-ml/src/integTest/resources/DriverIntegTest/input/ and asserts on it
in DriverTest.scala (shape/stage/λ-grid/best-model expectations, constants
at DriverTest.scala:944-945) and supervised/*Validator.scala (prediction
finiteness, non-negativity for Poisson, AUC thresholds —
BinaryClassifierAUCValidator.scala, BaseGLMTest.scala:226-231). These tests
read the reference's checked-in fixtures AS-IS and hold this implementation
to the same bars, so semantic drift from the reference fails loudly.

(The GAME yahoo-music train/test fixtures are NOT present in the reference
checkout — only a 6-row duplicateFeatures sample — so the GAME RMSE bars
from cli/game/training/DriverTest.scala:53,130,202 cannot be reproduced
here; the GLM fixtures below are complete.)
"""

from pathlib import Path

import numpy as np
import pytest

from photon_ml_tpu.data.avro_reader import build_index_map, read_labeled_points
from photon_ml_tpu.data.index_map import feature_key
from photon_ml_tpu.data.libsvm import read_libsvm
from photon_ml_tpu.evaluation.evaluators import area_under_roc_curve

REF_INPUT = Path(
    "/root/reference/photon-ml/src/integTest/resources/DriverIntegTest/input")

pytestmark = pytest.mark.skipif(
    not REF_INPUT.exists(), reason="reference fixtures not available")

# DriverTest.scala:944-945
EXPECTED_NUM_FEATURES = 14
EXPECTED_NUM_TRAINING_DATA = 250


def _train_glm(mat, y, task, lam=10.0, max_iter=80, tol=1e-6,
               optimizer="LBFGS"):
    """Train one GLM the way the reference driver does for one λ
    (ModelTraining.scala:102-214 semantics; reference defaults λ=10,
    L-BFGS, maxIter 80, tol 1e-6 per ml/Params.scala:42-203)."""
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.ops import GLMObjective
    from photon_ml_tpu.ops.features import DenseFeatures
    from photon_ml_tpu.ops.glm_objective import make_batch
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.optimization.solver import solve_glm

    dense = np.asarray(mat.todense() if sp.issparse(mat) else mat)
    batch = make_batch(DenseFeatures(jnp.asarray(dense)), jnp.asarray(y))
    config = GLMOptimizationConfiguration(
        max_iterations=max_iter, tolerance=tol,
        regularization_weight=lam,
        regularization_context=RegularizationContext(RegularizationType.L2),
        optimizer_type=OptimizerType(optimizer))
    objective = GLMObjective(loss_for_task(task))
    result = solve_glm(objective, batch, config,
                       jnp.zeros(dense.shape[1], jnp.float64))
    return np.asarray(result.x), result


# ---------------------------------------------------------------------------
# heart.avro — the central DriverTest fixture
# ---------------------------------------------------------------------------

def test_heart_avro_shape_and_labels():
    """DriverTest expects 250 rows x 14 features (13 + intercept) and binary
    labels (DataValidators logistic checks)."""
    mat, y, off, w, uids, imap = read_labeled_points(REF_INPUT / "heart.avro")
    assert mat.shape == (EXPECTED_NUM_TRAINING_DATA, EXPECTED_NUM_FEATURES)
    assert len(imap) == EXPECTED_NUM_FEATURES
    assert set(np.unique(y)) <= {0.0, 1.0}
    np.testing.assert_array_equal(w, 1.0)
    np.testing.assert_array_equal(off, 0.0)
    # Without intercept: the 13 original heart features, like the
    # reference's addIntercept=false runs (expectedNumFeatures = 13).
    mat13, *_ = read_labeled_points(REF_INPUT / "heart.avro",
                                    add_intercept=False)
    assert mat13.shape == (250, 13)


def test_heart_logistic_quality():
    """Train with reference defaults (λ=10, L-BFGS) on heart.avro; hold the
    model to the reference's validator bars (finite predictions, working
    classifier AUC) AND to the optimum of the identical objective found by
    an independent solver (scipy L-BFGS-B) — the strongest semantic-parity
    check available without a JVM: same convex objective, same optimum."""
    import scipy.optimize as so

    from photon_ml_tpu.types import TaskType

    mat, y, *_ = read_labeled_points(REF_INPUT / "heart.avro")
    # Tight tolerance so the comparison is optimum-vs-optimum (reference
    # defaults stop at |Δf| <= 1e-6·f0, slightly short of the minimizer).
    coef, result = _train_glm(mat, y, TaskType.LOGISTIC_REGRESSION,
                              max_iter=500, tol=1e-12)
    assert np.all(np.isfinite(coef))

    # Independent solve of Σ log1pexp semantics + λ/2‖w‖² (the reference's
    # LogisticLossFunction + L2Regularization, glm/LogisticLossFunction.scala
    # + L2Regularization.scala).
    dense = np.asarray(mat.todense())

    def nll(w):
        z = dense @ w
        return float(np.sum(np.logaddexp(0, z) - y * z) + 5.0 * (w @ w))

    ref = so.minimize(nll, np.zeros(dense.shape[1]), method="L-BFGS-B",
                      options={"maxiter": 500, "ftol": 1e-14})
    assert float(result.value) <= ref.fun * (1 + 1e-5)
    # Principled coefficient tolerance from strong convexity: the L2 term
    # 5·wᵀw makes the objective 10-strongly-convex, so the value bound
    # just asserted (f − f* ≤ 1e-5·f* ≈ 9.4e-4) implies
    # ‖w − w*‖ ≤ sqrt(2·9.4e-4/10) ≈ 1.4e-2. The Armijo-backtracking
    # solver stalls ~3e-4 from the optimum on this problem (measured for
    # BOTH the two-loop and compact-representation directions); atol=1e-3
    # sits between the observed stall and the provable bound.
    np.testing.assert_allclose(coef, ref.x, rtol=1e-3, atol=1e-3)
    # Keep a tighter signal than the provable bound: the measured stall is
    # ~3e-4 at the worst coefficient; the bulk of the vector sits well
    # below it. A genuine direction-quality regression (which the
    # loosened atol above would mask) trips this percentile check first.
    err = np.abs(coef - ref.x)
    assert float(np.median(err)) <= 3e-4, (
        f"median |coef - w*| = {np.median(err):.2e} — direction quality "
        "regressed vs the measured Armijo stall")

    auc_train = area_under_roc_curve(mat @ coef, y)
    assert 0.85 <= auc_train <= 1.0, auc_train

    # heart_validation is only 20 rows (96 label pairs) — assert the same
    # AUC an exact solver of this objective achieves (0.74), with slack.
    vmat, vy, *_ = read_labeled_points(
        REF_INPUT / "heart_validation.avro",
        index_map=build_index_map(REF_INPUT / "heart.avro"))
    auc_val = area_under_roc_curve(vmat @ coef, vy)
    assert 0.70 <= auc_val <= 1.0, auc_val


def test_heart_avro_vs_libsvm_identical_model():
    """heart.txt is the SAME dataset in LibSVM form (DriverTest's
    testLibSVMRunWithValidation trains on it with feature-dimension 13).
    Reading both formats and training with the same config must give the
    same coefficients — cross-format ingest parity."""
    from photon_ml_tpu.types import TaskType

    mat_a, y_a, *_rest = read_labeled_points(REF_INPUT / "heart.avro")
    imap = _rest[-1]
    mat_l, y_l = read_libsvm(REF_INPUT / "heart.txt", num_features=13)

    # Align columns: avro column order comes from the IndexMap; libsvm
    # column j holds feature "j+1" and the intercept is last.
    perm = [imap.get_index(feature_key(str(j + 1))) for j in range(13)]
    perm.append(imap.intercept_index)
    mat_a_aligned = np.asarray(mat_a.todense())[:, perm]

    np.testing.assert_array_equal(y_a, y_l)
    np.testing.assert_allclose(mat_a_aligned, np.asarray(mat_l.todense()),
                               rtol=1e-12)

    c_avro, _ = _train_glm(mat_a_aligned, y_a, TaskType.LOGISTIC_REGRESSION)
    c_lsvm, _ = _train_glm(np.asarray(mat_l.todense()), y_l,
                           TaskType.LOGISTIC_REGRESSION)
    np.testing.assert_allclose(c_avro, c_lsvm, rtol=1e-6, atol=1e-8)


def test_heart_driver_end_to_end(tmp_path):
    """The full GLM driver on the reference fixture, mirroring DriverTest's
    testRunWithDataValidation: default grid [10], LBFGS, stages through
    VALIDATED, one learned model per λ, best-model selected with λ=10
    (DriverTest.scala:148-152)."""
    from photon_ml_tpu.cli import glm_driver

    out = tmp_path / "out"
    glm_driver.run([
        "--training-data-directory", str(REF_INPUT / "heart.avro"),
        "--validating-data-directory",
        str(REF_INPUT / "heart_validation.avro"),
        "--output-directory", str(out),
        "--task", "LOGISTIC_REGRESSION",
        "--dtype", "float64",
    ])
    best = out / "best-model" / "model.txt"
    assert best.exists()
    # One model per λ in the default grid ("10") under all-models/<λ>/
    # (the reference's LEARNED_MODELS_TEXT layout).
    txts = sorted((out / "all-models").rglob("model.txt"))
    assert len(txts) == 1
    assert txts[0].parent.name == "10.0"
    # Best model text carries λ=10 in its fourth column
    # (the reference's model text format: name\tterm\tvalue\tlambda).
    first = best.read_text().strip().splitlines()[0].split("\t")
    assert float(first[3]) == 10.0


# ---------------------------------------------------------------------------
# linear_regression_train/val.avro — 1000 rows x 7 features
# (DriverTest.testDiagnosticGenerationProvider, DriverTest.scala:786)
# ---------------------------------------------------------------------------

def test_linear_regression_fixture_quality():
    from photon_ml_tpu.types import TaskType

    mat, y, *_rest = read_labeled_points(
        REF_INPUT / "linear_regression_train.avro")
    imap = _rest[-1]
    assert mat.shape == (1000, 7)  # 6 features + intercept

    coef, _ = _train_glm(mat, y, TaskType.LINEAR_REGRESSION, lam=0.0)
    pred = mat @ coef
    # PredictionFiniteValidator + MaximumDifferenceValidator semantics
    # (BaseGLMTest.scala:124-126; bound = 10 * inlier σ).
    assert np.all(np.isfinite(pred))
    resid = pred - y
    assert np.abs(resid).max() <= 10 * y.std()
    # The fit must explain the fixture far better than the mean predictor.
    r2 = 1 - np.sum(resid ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.5, r2

    vmat, vy, *_ = read_labeled_points(
        REF_INPUT / "linear_regression_val.avro", index_map=imap)
    vresid = vmat @ coef - vy
    vr2 = 1 - np.sum(vresid ** 2) / np.sum((vy - vy.mean()) ** 2)
    assert vr2 > 0.5, vr2


# ---------------------------------------------------------------------------
# poisson_test.avro — ResponsePredictionFieldNames format (Pig schema:
# response/feature floats wrapped in [null, X] unions), 4521 rows x 27 cols
# (DriverTest.scala:788 reads it with FieldNamesType.RESPONSE_PREDICTION)
# ---------------------------------------------------------------------------

def test_poisson_response_prediction_format():
    from photon_ml_tpu.types import TaskType

    mat, y, off, w, uids, imap = read_labeled_points(
        REF_INPUT / "poisson_test.avro")
    assert mat.shape[0] == 4521
    assert mat.shape[1] == 27  # 26 features + intercept (DriverTest: 27)
    assert np.all(y >= 0)  # DataValidators Poisson non-negative response

    coef, _ = _train_glm(mat, y, TaskType.POISSON_REGRESSION, lam=10.0,
                         max_iter=40)
    # NonNegativePredictionValidator: Poisson mean = exp(margin) > 0, finite.
    mean = np.exp(mat @ coef)
    assert np.all(np.isfinite(mean))
    assert np.all(mean >= 0)


# ---------------------------------------------------------------------------
# a9a (LibSVM) + logistic_regression_val.avro — the adult dataset pair
# (32561 train / 16281 validation, 124 features incl. intercept,
# DriverTest.scala:787)
# ---------------------------------------------------------------------------

def test_a9a_train_avro_validation():
    from photon_ml_tpu.types import TaskType

    mat, y = read_libsvm(REF_INPUT / "a9a", num_features=123)
    assert mat.shape == (32561, 124)
    assert set(np.unique(y)) == {0.0, 1.0}

    coef, _ = _train_glm(mat, y, TaskType.LOGISTIC_REGRESSION, lam=10.0,
                         max_iter=50)

    # Validate against the reference's avro conversion of a9a.t: align
    # avro columns (named "1".."123" + intercept) with libsvm order. The
    # index map comes from the TRAIN feature space (the reference trains
    # the map on training data; one indicator never fires in validation).
    from photon_ml_tpu.data.index_map import IndexMap

    imap = IndexMap.from_name_terms(
        [(str(j + 1), "") for j in range(123)], add_intercept=True)
    vmat, vy, *_rest = read_labeled_points(
        REF_INPUT / "logistic_regression_val.avro", index_map=imap)
    assert vmat.shape == (16281, 124)
    perm = [imap.get_index(feature_key(str(j + 1))) for j in range(123)]
    perm.append(imap.intercept_index)
    vdense = np.asarray(vmat.todense())[:, perm]

    auc = area_under_roc_curve(vdense @ coef, vy)
    # L2-regularized logistic on a9a reaches ~0.90 validation AUC; any
    # semantic drift (loss, regularization, ingest alignment) falls well
    # below this bar.
    assert auc >= 0.88, auc


GAME_INPUT = Path(
    "/root/reference/photon-ml/src/integTest/resources/GameIntegTest/input")


def test_duplicate_features_rejected_like_reference():
    """The reference hard-rejects records with duplicate (name, term)
    features (AvroDataReader.scala:306-311) and ships a fixture for it;
    this implementation must fail the same input the same way, not
    silently sum the duplicates into a different model."""
    fixture = GAME_INPUT / "duplicateFeatures" / "yahoo-music-train.avro"
    with pytest.raises(ValueError, match="duplicate"):
        read_labeled_points(fixture)

    from photon_ml_tpu.data.avro_reader import read_game_dataset

    with pytest.raises(ValueError, match="duplicate"):
        read_game_dataset(fixture, id_types=[])
