"""ModelTracker / CoefficientSummary tests.

Reference pattern: ml/supervised/model/ModelTracker.scala pairs optimization
states with per-iteration models; CoefficientSummary.scala accumulates
coefficient distribution stats (unit-tested in
photon-ml/src/test/scala/.../supervised/model/CoefficientSummaryTest.scala).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.estimators.model_training import train_glm_models
from photon_ml_tpu.models import (
    CoefficientSummary,
    ModelTracker,
    summarize_coefficients,
)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import LogisticRegressionModel
from photon_ml_tpu.optimization import (
    minimize_lbfgs,
    minimize_owlqn,
    minimize_tron,
)
from photon_ml_tpu.types import TaskType


def _quad(x):
    c = jnp.asarray([1.0, -2.0, 3.0])
    return jnp.sum((x - c) ** 2)


@pytest.mark.parametrize(
    "minimize, kwargs",
    [(minimize_lbfgs, {}), (minimize_tron, {}),
     (minimize_owlqn, {"l1_weight": 0.01})],
    ids=["lbfgs", "tron", "owlqn"])
def test_coef_history_recorded(minimize, kwargs):
    res = minimize(_quad, jnp.zeros(3), track_coefficients=True,
                   tol=1e-10, **kwargs)
    hist = np.asarray(res.coef_history)
    iters = int(res.iterations)
    assert hist.shape[1] == 3
    # Row 0 is the start, row `iters` the final iterate.
    np.testing.assert_allclose(hist[0], np.zeros(3), atol=0)
    np.testing.assert_allclose(hist[iters], np.asarray(res.x), atol=1e-12)


def test_coef_history_off_by_default():
    res = minimize_lbfgs(_quad, jnp.zeros(3))
    assert res.coef_history is None


def test_model_tracker_from_training():
    rng = np.random.default_rng(0)
    n, d = 500, 8
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)

    trained = train_glm_models(
        x, y, TaskType.LOGISTIC_REGRESSION, regularization_weights=[1.0],
        max_iterations=25, track_models=True)[0]
    tracker = trained.tracker
    assert tracker is not None
    assert tracker.num_iterations == int(trained.result.iterations)
    assert len(tracker.models) == tracker.num_iterations + 1
    # Objective values are non-increasing along the recorded states.
    values = [s.value for s in tracker.states]
    assert all(b <= a + 1e-9 for a, b in zip(values, values[1:]))
    # The last tracked model matches the returned model.
    np.testing.assert_allclose(
        np.asarray(tracker.models[-1].coefficients.means),
        np.asarray(trained.model.coefficients.means), atol=1e-12)
    # States carry finite telemetry.
    assert all(np.isfinite(s.value) and np.isfinite(s.grad_norm)
               for s in tracker.states)


def test_tracker_absent_by_default():
    x = np.random.default_rng(1).normal(size=(50, 3))
    y = (x[:, 0] > 0).astype(float)
    trained = train_glm_models(
        x, y, TaskType.LOGISTIC_REGRESSION, regularization_weights=[1.0],
        max_iterations=5)[0]
    assert trained.tracker is None
    assert trained.result.coef_history is None


def test_coefficient_summary_stats():
    s = CoefficientSummary.of([1.0, 2.0, 3.0, 4.0])
    assert s.count == 4
    assert s.min == 1.0 and s.max == 4.0
    assert s.mean == pytest.approx(2.5)
    assert s.std_dev == pytest.approx(np.std([1, 2, 3, 4], ddof=1))
    # Reference's sorted-index quantile estimator: sorted[q*n/4].
    assert s.first_quartile() == 2.0
    assert s.median() == 3.0
    assert s.third_quartile() == 4.0
    assert "# samples = [4]" in str(s)


def test_coefficient_summary_empty_is_nan_not_crash():
    s = CoefficientSummary()
    assert np.isnan(s.mean) and np.isnan(s.min) and np.isnan(s.max)
    assert np.isnan(s.median()) and np.isnan(s.first_quartile())
    assert "# samples = [0]" in str(s)


def test_coefficient_summary_single_class():
    # diagnostics re-exports the same canonical class.
    from photon_ml_tpu.diagnostics import CoefficientSummary as DiagSummary

    assert DiagSummary is CoefficientSummary


def test_metric_metadata():
    from photon_ml_tpu.evaluation import (
        METRIC_METADATA,
        build_evaluator,
        metadata_for,
    )

    auc = METRIC_METADATA["AUC"]
    assert auc.higher_is_better and auc.value_range == (0.0, 1.0)
    assert not METRIC_METADATA["RMSE"].higher_is_better
    # metadata_for agrees with each evaluator's own ordering.
    for spec in ["AUC", "RMSE", "LOGISTIC_LOSS", "AUC:userId",
                 "PRECISION@5:userId"]:
        ev = build_evaluator(spec)
        meta = metadata_for(ev)
        assert meta.higher_is_better == ev.higher_is_better, spec
        assert meta.name == ev.name
    d = auc.to_dict()
    assert d["higherIsBetter"] is True and d["range"] == (0.0, 1.0)


def test_summarize_coefficients_across_models():
    models = [
        LogisticRegressionModel(Coefficients(jnp.asarray([0.0, 10.0]))),
        LogisticRegressionModel(Coefficients(jnp.asarray([2.0, 20.0]))),
        LogisticRegressionModel(Coefficients(jnp.asarray([4.0, 30.0]))),
    ]
    sums = summarize_coefficients(models)
    assert len(sums) == 2
    assert sums[0].mean == pytest.approx(2.0)
    assert sums[1].min == 10.0 and sums[1].max == 30.0


def test_summarize_trackers_glmix(rng):
    """Aggregated GAME telemetry: per coordinate per update, solve counts,
    convergence-reason histogram and iteration/objective stats (reference:
    RandomEffectOptimizationTracker.countConvergenceReasons +
    getNumIterationStats)."""
    import json

    from photon_ml_tpu.models.tracking import summarize_trackers
    from tests.test_coordinate_descent import (
        build_coordinates,
        make_glmix_data,
    )
    from photon_ml_tpu.algorithm import CoordinateDescent
    from photon_ml_tpu.types import TaskType

    data, *_ = make_glmix_data(rng, n=300)
    cd = CoordinateDescent(build_coordinates(data),
                           TaskType.LOGISTIC_REGRESSION)
    res = cd.run(num_iterations=2, seed=3)
    summary = summarize_trackers(res.trackers)

    assert set(summary) == set(res.trackers)
    for name, per_update in summary.items():
        assert len(per_update) == 2  # one entry per CD update
        for s in per_update:
            assert s["numSolves"] >= 1
            assert sum(s["convergenceReasons"].values()) == s["numSolves"]
            assert all(k in ("NOT_CONVERGED", "MAX_ITERATIONS",
                             "FUNCTION_VALUES_CONVERGED",
                             "GRADIENT_CONVERGED",
                             "OBJECTIVE_NOT_IMPROVING")
                       for k in s["convergenceReasons"])
            assert s["iterations"]["max"] >= s["iterations"]["mean"] >= 0
            assert np.isfinite(s["finalValue"]["mean"])
    # perUser aggregates one solve per entity.
    n_entities = sum(
        c.shape[0]
        for c in cd.coordinates["perUser"].params_of(
            cd.coordinates["perUser"].initialize_model()))
    assert summary["perUser"][0]["numSolves"] == n_entities
    json.dumps(summary)  # JSON-ready for model-metadata.json
