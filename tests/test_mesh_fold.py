"""Mesh-parallel streamed training: device-count invariance of the
sharded objective fold (ops/sharded_objective.py `mesh=`,
data/shard_cache.py `devices=`).

The PR-5 contract extended one axis: the fold combines per-shard
partials in FIXED GLOBAL SHARD ORDER no matter which mesh device
computed them, and a given executable is bitwise-deterministic on every
device of a homogeneous mesh — so with the default "ordered" combine
the device count changes NOTHING:

- mesh sizes {1, 2, 4} produce bit-identical (value, gradient, Hvp) and
  bit-identical streamed L-BFGS / TRON solutions, all equal to the
  non-mesh fold (a 1-device mesh IS the single-device code path);
- residency independence (resident == eviction-forced == zero-prefetch)
  is preserved under a mesh, with the HBM budget binding PER DEVICE;
- per-device kernel compile counts stay within the per-BUCKET budgets
  (TracingGuard-asserted): a bigger mesh never buys a kernel more
  compiles.

The "local" combine (per-device left-folds + fixed device-order apex —
the psum/treeAggregate shape) is deterministic for fixed (shards,
devices), identical to "ordered" at 1 device, and within documented f32
reassociation bounds otherwise.

The subprocess test drives the REAL total-device-count axis: the CLI
driver runs in children whose jax sees exactly N devices
(`multi_device` fixture) and the written model bytes must not depend on
N.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.shard_cache import DeviceShardCache
from photon_ml_tpu.ops.glm_objective import GLMObjective
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.sharded_objective import ShardedGLMObjective
from photon_ml_tpu.optimization.glm_lbfgs import (
    minimize_lbfgs_glm_streaming,
)
from photon_ml_tpu.optimization.tron import minimize_tron_streaming
from photon_ml_tpu.parallel import make_mesh, mesh_device_list
from photon_ml_tpu.types import TaskType

from tests.test_shard_cache import FakeStream


@pytest.fixture
def problem(rng):
    n, d = 1003, 41
    X = sp.random(n, d, density=0.1, random_state=11, format="csr")
    X.data[:] = rng.normal(0, 1, X.nnz)
    y = (rng.random(n) < 0.5).astype(float)
    off = rng.normal(0, 0.1, n)
    w = rng.gamma(1.0, 1.0, n)
    return X, y, off, w


def _bits(x):
    return np.asarray(x).tobytes()


def _sobj(problem, mesh_n=None, budget=None, batch_rows=128,
          combine="ordered", prefetch_depth=None):
    X, y, off, w = problem
    mesh = make_mesh(mesh_n) if mesh_n else None
    devices = (mesh_device_list(mesh)
               if mesh is not None and mesh_n > 1 else None)
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, batch_rows, off, w), "g",
        hbm_budget_bytes=budget, devices=devices)
    if prefetch_depth is not None:
        cache.prefetch_depth = prefetch_depth
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    return ShardedGLMObjective(obj, cache, mesh=mesh, combine=combine)


def _block_bytes(problem):
    return max(e.feature_bytes
               for e in _sobj(problem).cache.entries)


def test_mesh_value_grad_hvp_bitwise_across_mesh_sizes(problem, rng):
    """The acceptance contract: every fold quantity is bit-identical for
    mesh sizes {1, 2, 4} and equal to the non-mesh fold."""
    X = problem[0]
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    vec = jnp.asarray(rng.normal(0, 1.0, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.7, jnp.float32)

    ref = _sobj(problem)
    z_ref, f_ref, g_ref = ref.margins_value_grad(coef, l2)
    hv_ref = ref.hessian_vector(vec, ref.curvature_list(z_ref), l2)
    for mesh_n in (1, 2, 4):
        s = _sobj(problem, mesh_n=mesh_n)
        z, f, g = s.margins_value_grad(coef, l2)
        assert _bits(f) == _bits(f_ref), mesh_n
        assert _bits(g) == _bits(g_ref), mesh_n
        # per-shard margins are row-local device state — same bits no
        # matter which device holds them
        for za, zb in zip(z, z_ref):
            assert _bits(za) == _bits(zb)
        hv = s.hessian_vector(vec, s.curvature_list(z), l2)
        assert _bits(hv) == _bits(hv_ref), mesh_n
        if mesh_n == 1:
            # a 1-device mesh IS the single-device fold
            assert s.devices is None and s.mesh is None


def test_mesh_residency_independence(problem, rng):
    """resident == eviction-forced == zero-prefetch under a 2-device
    mesh, bit for bit, with the budget binding per device."""
    X = problem[0]
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.7, jnp.float32)
    block = _block_bytes(problem)

    resident = _sobj(problem, mesh_n=2)
    fr, gr = resident.value_and_grad(coef, l2)
    for budget, depth in [(block, 2), (2 * block, 0)]:
        spill = _sobj(problem, mesh_n=2, budget=budget,
                      prefetch_depth=depth)
        fs, gs = spill.value_and_grad(coef, l2)
        assert _bits(fs) == _bits(fr)
        assert _bits(gs) == _bits(gr)
        stats = spill.cache.stats()
        assert stats["evictions"] > 0
        assert stats["mesh_devices"] == 2
        # the budget is PER DEVICE: each slot honors it independently
        # (the in-hand block may transiently exceed it, as in PR 5)
        assert all(b <= budget + block
                   for b in stats["per_device_bytes"])


@pytest.mark.slow
def test_mesh_streaming_solvers_bitwise_across_mesh_sizes(problem):
    """Full streamed L-BFGS and TRON solves write the same coefficient
    bits for mesh sizes {1, 2, 4} (spill-forced) as without a mesh."""
    X = problem[0]
    x0 = jnp.zeros(X.shape[1], jnp.float32)
    l2 = jnp.asarray(0.5, jnp.float32)
    block = _block_bytes(problem)

    ref_l = minimize_lbfgs_glm_streaming(_sobj(problem), x0, l2,
                                         max_iter=20)
    ref_t = minimize_tron_streaming(_sobj(problem), x0, l2, max_iter=6)
    for mesh_n in (1, 2, 4):
        s = _sobj(problem, mesh_n=mesh_n, budget=block)
        got = minimize_lbfgs_glm_streaming(s, x0, l2, max_iter=20)
        assert _bits(got.x) == _bits(ref_l.x), mesh_n
        assert int(got.iterations) == int(ref_l.iterations)
        assert int(got.reason) == int(ref_l.reason)
        if mesh_n > 1:
            assert s.cache.stats()["evictions"] > 0
        t = minimize_tron_streaming(
            _sobj(problem, mesh_n=mesh_n, budget=block), x0, l2,
            max_iter=6)
        assert _bits(t.x) == _bits(ref_t.x), mesh_n


def test_local_combine_bounded_reassociation(problem, rng):
    """combine="local" (per-device folds + device-order apex): identical
    to "ordered" at 1 device, deterministic and within f32
    reassociation bounds at 4."""
    X = problem[0]
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.7, jnp.float32)

    f0, g0 = _sobj(problem).value_and_grad(coef, l2)
    f1, g1 = _sobj(problem, mesh_n=1,
                   combine="local").value_and_grad(coef, l2)
    assert _bits(f1) == _bits(f0) and _bits(g1) == _bits(g0)

    f4a, g4a = _sobj(problem, mesh_n=4,
                     combine="local").value_and_grad(coef, l2)
    f4b, g4b = _sobj(problem, mesh_n=4,
                     combine="local").value_and_grad(coef, l2)
    # deterministic for fixed (shards, devices)...
    assert _bits(f4a) == _bits(f4b) and _bits(g4a) == _bits(g4b)
    # ...and within the documented reassociation bound of "ordered"
    np.testing.assert_allclose(np.asarray(f4a), np.asarray(f0),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g4a), np.asarray(g0),
                               rtol=2e-4, atol=1e-6)


def test_mesh_per_device_budget_and_placement(problem):
    """Blocks place round-robin (block i on device i mod D), spill
    re-uploads return to the assigned device, and each device's
    resident bytes honor the budget independently."""
    import jax

    X = problem[0]
    devices = jax.devices()[:4]
    mesh = make_mesh(4)
    assert mesh_device_list(mesh) == devices
    block = _block_bytes(problem)
    cache = DeviceShardCache.from_stream(
        FakeStream(problem[0], problem[1], 128, problem[2], problem[3]),
        "g", hbm_budget_bytes=block, devices=devices)
    assert cache.n_slots == 4
    for e in cache.entries:
        assert e.slot == e.index % 4
        assert e.device is devices[e.slot]
        for arr in (e.labels, e.offsets, e.weights):
            assert arr.devices() == {devices[e.slot]}
    # replay an epoch: every handed-out block is resident on ITS device
    for b in cache.blocks(prefetch_depth=0):
        assert b.slot == b.index % 4
        assert b.feats.values.devices() == {devices[b.slot]}
    stats = cache.stats()
    assert stats["mesh_devices"] == 4
    assert len(stats["per_device_bytes"]) == 4
    assert sum(stats["per_device_resident_shards"]) == \
        stats["resident_shards"]
    assert all(b <= block for b in stats["per_device_bytes"])


@pytest.mark.slow
def test_mesh_trace_budgets_per_bucket_not_per_device(problem):
    """Every per-device kernel is registered in the guard and stays
    within its per-BUCKET budget across a λ-grid sweep + TRON — and no
    single kernel's count grows with the mesh size."""
    X = problem[0]
    x0 = jnp.zeros(X.shape[1], jnp.float32)
    block = _block_bytes(problem)

    counts_by_mesh = {}
    for mesh_n in (1, 2, 4):
        s = _sobj(problem, mesh_n=mesh_n, budget=block)
        for l2 in (0.1, 1.0, 10.0):
            minimize_lbfgs_glm_streaming(
                s, x0, jnp.asarray(l2, jnp.float32), max_iter=8)
        minimize_tron_streaming(s, x0, jnp.asarray(0.5, jnp.float32),
                                max_iter=4)
        s.assert_trace_budget()
        counts = s.guard.counts()
        budgets = s.trace_budgets()
        assert set(counts) <= set(budgets)
        for name, c in counts.items():
            assert c <= budgets[name], (mesh_n, name, c, budgets[name])
        if mesh_n > 1:
            # every per-device kernel family is registered per device
            for k in range(mesh_n):
                assert f"sharded:init@d{k}" in counts
            assert "sharded:combine" in counts
        counts_by_mesh[mesh_n] = counts

    # compiles scale with bucket count, not device count: the max count
    # of any single registered kernel is no larger on the 4-device mesh
    # than on the 1-device fold
    per_kernel_max = {m: max(c.values())
                      for m, c in counts_by_mesh.items()}
    assert per_kernel_max[4] <= per_kernel_max[1] + 0


def test_mesh_fold_telemetry_spans(problem, rng):
    """Mesh folds emit one span family per device-fold stage
    (device_fold:dK) plus the cross-device combine, so Perfetto traces
    and the stage attribution break the accumulate down per device."""
    from photon_ml_tpu import telemetry

    X = problem[0]
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    telemetry.reset()
    telemetry.enable(trace=True)
    try:
        s = _sobj(problem, mesh_n=2)
        s.value_and_grad(coef, 0.5)
        att = telemetry.stage_attribution()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert "accumulate" in att
    assert "device_fold:d0" in att and "device_fold:d1" in att
    assert "cross_device_combine" in att
    # the non-mesh fold keeps PR-5's span structure untouched
    telemetry.reset()
    telemetry.enable()
    try:
        _sobj(problem).value_and_grad(coef, 0.5)
        att = telemetry.stage_attribution()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert "accumulate" in att
    assert not any(k.startswith("device_fold") for k in att)


def test_mesh_validation_errors(problem):
    """Mis-wiring fails loudly: mesh without a placed cache, cache on
    different devices, bad combine, 2-D mesh."""
    import jax

    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    mesh = make_mesh(2)
    unplaced = DeviceShardCache.from_stream(
        FakeStream(X, y, 200, off, w), "g")
    with pytest.raises(ValueError, match="same devices"):
        ShardedGLMObjective(obj, unplaced, mesh=mesh)
    wrong = DeviceShardCache.from_stream(
        FakeStream(X, y, 200, off, w), "g",
        devices=list(reversed(jax.devices()[:2])))
    with pytest.raises(ValueError, match="same devices"):
        ShardedGLMObjective(obj, wrong, mesh=mesh)
    with pytest.raises(ValueError, match="combine"):
        ShardedGLMObjective(obj, unplaced, combine="tree")
    # the converse mis-wiring: mesh-placed cache, mesh-less objective
    placed = DeviceShardCache.from_stream(
        FakeStream(X, y, 200, off, w), "g",
        devices=mesh_device_list(mesh))
    with pytest.raises(ValueError, match="without a mesh"):
        ShardedGLMObjective(obj, placed)
    from photon_ml_tpu.parallel import make_mesh_2d

    with pytest.raises(ValueError, match="1-D mesh"):
        mesh_device_list(make_mesh_2d(2, 2))


def test_forced_cpu_device_env_scrubs_and_pins():
    """The shared child-env builder (conftest multi_device + the bench
    mesh children) replaces an inherited device-count force and pins
    the platform."""
    from photon_ml_tpu.utils.virtual_devices import forced_cpu_device_env

    env = forced_cpu_device_env(3, {
        "XLA_FLAGS": "--foo --xla_force_host_platform_device_count=8",
        "JAX_PLATFORMS": "tpu", "OTHER": "kept"})
    assert env["XLA_FLAGS"] == \
        "--foo --xla_force_host_platform_device_count=3"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["OTHER"] == "kept"


def test_streaming_coordinate_mesh_mismatch(problem):
    """A shared sharded objective must carry the coordinate's mesh."""
    from photon_ml_tpu.algorithm.coordinates import (
        StreamingFixedEffectCoordinate,
    )
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
    )

    X, y, off, w = problem
    mesh = make_mesh(2)
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 200, off, w), "g",
        devices=mesh_device_list(mesh))
    cfg = GLMOptimizationConfiguration.parse("5,1e-6,1.0,1.0,LBFGS,L2")
    coord = StreamingFixedEffectCoordinate(
        name="fe", cache=cache, feature_shard_id="g",
        task_type=TaskType.LOGISTIC_REGRESSION, config=cfg, mesh=mesh)
    assert coord.sharded_objective.devices == mesh_device_list(mesh)
    with pytest.raises(ValueError, match="same mesh"):
        StreamingFixedEffectCoordinate(
            name="fe", cache=cache, feature_shard_id="g",
            task_type=TaskType.LOGISTIC_REGRESSION, config=cfg,
            sharded_objective=coord.sharded_objective, mesh=None)
    model, result = coord.solve()
    assert model.glm.coefficients.means.shape == (X.shape[1],)
    assert int(result.iterations) > 0


_CHILD_DRIVER = """
import hashlib
import json
from pathlib import Path

import jax

n_devices, out_dir, train_dir = __N__, __OUT__, __TRAIN__
assert jax.device_count() == n_devices, (
    f"child expected {n_devices} devices, jax sees "
    f"{jax.device_count()}")

from photon_ml_tpu.cli import game_training_driver
from photon_ml_tpu.io.avro_codec import read_container

summary = game_training_driver.run([
    "--train-input-dirs", train_dir,
    "--output-dir", out_dir,
    "--task-type", "LOGISTIC_REGRESSION",
    "--fixed-effect-data-configurations", "fixed:global",
    "--fixed-effect-optimization-configurations",
    "fixed:25,1e-7,1.0,1.0,LBFGS,L2",
    "--updating-sequence", "fixed",
    "--stream-train", "--batch-rows", "48",
    "--hbm-budget", "8K", "--mesh-devices", str(n_devices),
])
info = summary["stream_train"]
assert info["mesh_devices"] == n_devices
assert "streamTrain" not in summary  # legacy alias removed
records = list(read_container(
    Path(out_dir) / "best" / "fixed-effect" / "fixed" / "coefficients"
    / "part-00000.avro"))
print("COEFF_SHA", hashlib.sha256(
    json.dumps(records, sort_keys=True).encode()).hexdigest())
print("MESH_CHILD_OK", n_devices)
"""


@pytest.mark.slow
def test_driver_mesh_model_bytes_independent_of_total_device_count(
        tmp_path, rng, multi_device):
    """End-to-end on the REAL device-count axis: the spill-mode driver
    runs in subprocesses whose jax sees exactly N in {1, 2, 4} devices
    (this harness is pinned to 8 virtual devices; a real host has
    however many chips it has), with --mesh-devices N — the decoded
    coefficient records must be identical across N (the container
    header embeds a random sync marker, so decoded records are the
    byte-identity comparison unit). Slow-marked: three forced-device
    subprocess training runs (the in-process bitwise mesh-size parity
    stays in tier-1 via test_mesh_streaming_solvers_bitwise_...)."""
    from tests.test_cli_drivers import _write_sparse_fe_avro

    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=150)
    shas = {}
    for n_dev in (1, 2, 4):
        out = tmp_path / f"out{n_dev}"
        code = (_CHILD_DRIVER
                .replace("__N__", str(n_dev))
                .replace("__OUT__", repr(str(out)))
                .replace("__TRAIN__", repr(str(train))))
        proc = multi_device(n_dev, code, timeout=420)
        assert f"MESH_CHILD_OK {n_dev}" in proc.stdout
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("COEFF_SHA")][0]
        shas[n_dev] = line.split()[1]
    # decoded coefficient records identical for every total device count
    assert len(set(shas.values())) == 1, shas
