"""PalDB 1.1 read-only store interop (VERDICT r2 item 4).

The reference's feature-index stores are PalDB (ml/util/PalDBIndexMap.scala:
43-220, built by ml/FeatureIndexingJob.scala:145-174); its GAME integ
fixtures ship pre-built stores. These tests hold the parser to the
reference's own artifacts: full decode of every fixture store, forward /
reverse consistency, partitioned-offset semantics, and the training
driver's --feature-index-dir plumbing.
"""

from pathlib import Path

import numpy as np
import pytest

from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.data.paldb import (
    discover_namespaces,
    java_hash_partition,
    load_feature_index_maps,
    load_paldb_index_map,
    load_paldb_index_maps,
    read_paldb_store,
)

GAME_INPUT = Path(
    "/root/reference/photon-ml/src/integTest/resources/GameIntegTest/input")

pytestmark = pytest.mark.skipif(
    not GAME_INPUT.exists(), reason="reference fixtures not available")


def test_java_hash_partition_matches_java_semantics():
    # Java String.hashCode golden values.
    assert java_hash_partition("", 4) == 0
    # "polygenelubricants".hashCode() == Integer.MIN_VALUE (classic case);
    # Spark nonNegativeMod keeps the partition non-negative.
    for p in (1, 2, 3, 7):
        part = java_hash_partition("polygenelubricants", p)
        assert 0 <= part < p


def test_discover_namespaces():
    assert discover_namespaces(GAME_INPUT / "feature-indexes") == {
        "shard1": 1, "shard2": 1, "shard3": 1}
    assert discover_namespaces(
        GAME_INPUT / "test-with-uid-feature-indexes") == {
        "globalShard": 1, "songShard": 1, "userShard": 1}


def test_store_decodes_fully_and_bidirectionally():
    """Every entry decodes; name->idx and idx->name directions agree
    (PalDBIndexMapBuilder stores both, PalDBIndexMapBuilder.scala:45-49)."""
    store = GAME_INPUT / "feature-indexes" / "paldb-partition-shard1-0.dat"
    fwd, rev = {}, {}
    for k, v in read_paldb_store(store):
        (fwd if isinstance(k, str) else rev)[k] = v
    assert len(fwd) == len(rev) == 15045
    for name, idx in fwd.items():
        assert rev[idx] == name
    assert sorted(fwd.values()) == list(range(15045))


@pytest.mark.parametrize("dirname,expected", [
    ("feature-indexes", {"shard1": 15045, "shard2": 15015, "shard3": 31}),
    ("test-with-uid-feature-indexes",
     {"globalShard": 7234, "songShard": 7204, "userShard": 7204}),
])
def test_fixture_stores_load_as_index_maps(dirname, expected):
    maps = load_paldb_index_maps(GAME_INPUT / dirname)
    assert {ns: len(m) for ns, m in maps.items()} == expected
    for ns, m in maps.items():
        # The reference's key convention (name + \x01 + term) means the
        # intercept key resolves directly.
        assert m.intercept_index >= 0
        assert m.get_index(INTERCEPT_KEY) == m.intercept_index
        # Round-trip: every key looks up to its index and back.
        for key, idx in m.key_items():
            assert m.get_index(key) == idx
            assert m.get_feature_name(idx) == key
        # Indices are a clean 0..n-1 range (offset semantics validated
        # inside the loader as well).
        assert m.get_index("no-such-feature\x01") == -1


def test_partition_offsets_match_reference_semantics(monkeypatch, tmp_path):
    """Multi-partition layout: global idx = internal idx + cumulative
    feature count of earlier partitions, in partition order
    (PalDBIndexMap.load, :71-100). The fixtures are single-partition, so
    synthesize a 2-partition store: split fixture keys with the
    reference's hash partitioner, re-number each partition's internal
    indices from 0 (exactly what FeatureIndexingJob produces), and serve
    the two synthetic stores through read_paldb_store."""
    import photon_ml_tpu.data.paldb as paldb_mod

    src = load_paldb_index_map(GAME_INPUT / "feature-indexes", "shard3", 1)
    keys = sorted(k for k, _ in src.key_items())
    parts = {0: [], 1: []}
    for k in keys:
        parts[java_hash_partition(k, 2)].append(k)
    assert parts[0] and parts[1]  # both partitions populated

    def fake_store(path):
        name = Path(path).name
        part = int(name.rsplit("-", 1)[1].split(".")[0])
        assert name.startswith("paldb-partition-shard3-")
        for internal, k in enumerate(parts[part]):
            yield k, internal          # name -> internal idx
            yield internal, k          # idx -> name (reverse direction)

    monkeypatch.setattr(paldb_mod, "read_paldb_store", fake_store)
    m = paldb_mod.load_paldb_index_map(tmp_path, "shard3", 2)
    # Partition 0 keys keep their internal indices; partition 1 keys are
    # offset by len(partition 0) — the reference's cumulative-offset rule.
    for internal, k in enumerate(parts[0]):
        assert m.get_index(k) == internal
    for internal, k in enumerate(parts[1]):
        assert m.get_index(k) == internal + len(parts[0])
    assert len(m) == len(keys)

    # A key planted in the WRONG partition must fail the hash validation,
    # never silently mis-index.
    swapped = {0: parts[1], 1: parts[0]}

    def wrong_store(path):
        part = int(Path(path).name.rsplit("-", 1)[1].split(".")[0])
        for internal, k in enumerate(swapped[part]):
            yield k, internal

    monkeypatch.setattr(paldb_mod, "read_paldb_store", wrong_store)
    with pytest.raises(ValueError, match="hashes to partition"):
        paldb_mod.load_paldb_index_map(tmp_path, "shard3", 2)


def test_load_feature_index_maps_both_formats(tmp_path):
    # PalDB format
    maps = load_feature_index_maps(GAME_INPUT / "feature-indexes")
    assert set(maps) == {"shard1", "shard2", "shard3"}
    # JSON format (this package's own stores)
    m = IndexMap({feature_key("a"): 0, feature_key("b"): 1})
    m.save(tmp_path / "myShard.json")
    maps2 = load_feature_index_maps(tmp_path)
    assert set(maps2) == {"myShard"}
    assert maps2["myShard"].get_index(feature_key("b")) == 1


def test_training_driver_accepts_feature_index_dir(tmp_path):
    """--feature-index-dir pointing at reference PalDB stores drives a real
    (tiny) GAME training run with the preloaded index space."""

    from photon_ml_tpu.cli.game_training_driver import run as train_run
    from photon_ml_tpu.data.paldb import load_paldb_index_map
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container
    from photon_ml_tpu.data.index_map import split_key

    imap = load_paldb_index_map(GAME_INPUT / "feature-indexes", "shard3", 1)
    keys = [k for k, _ in imap.key_items() if k != INTERCEPT_KEY][:6]
    rng = np.random.default_rng(0)
    records = []
    for i in range(40):
        feats = []
        for k in rng.choice(len(keys), size=3, replace=False):
            name, term = split_key(keys[int(k)])
            feats.append({"name": name, "term": term,
                          "value": float(rng.normal())})
        records.append({
            "uid": f"u{i}", "label": float(rng.integers(0, 2)),
            "features": feats, "weight": 1.0, "offset": 0.0,
            "metadataMap": {"userId": f"user{i % 5}"}})
    data_dir = tmp_path / "train"
    data_dir.mkdir()
    write_container(data_dir / "part-0.avro",
                    schemas.TRAINING_EXAMPLE, records)

    out = train_run([
        "--train-input-dirs", str(data_dir),
        "--output-dir", str(tmp_path / "out"),
        "--task-type", "LOGISTIC_REGRESSION",
        "--feature-index-dir", str(GAME_INPUT / "feature-indexes"),
        "--fixed-effect-data-configurations", "fixed:shard3",
        "--fixed-effect-optimization-configurations",
        "fixed:10,1e-4,1.0,1,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--num-iterations", "1",
    ])
    assert out["numRows"] == 40
    # The model was trained in the PalDB store's 31-feature index space.
    model_txt = list((tmp_path / "out" / "best").rglob("*.avro"))
    assert model_txt, "saved model artifacts missing"


def test_glm_driver_accepts_offheap_indexmap_dir(tmp_path):
    """--offheap-indexmap-dir (the reference's OFFHEAP_INDEXMAP_DIR flag)
    trains a GLM in a reference PalDB store's index space."""
    from photon_ml_tpu.cli.glm_driver import run as glm_run
    from photon_ml_tpu.data.index_map import split_key
    from photon_ml_tpu.data.paldb import load_paldb_index_map
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    imap = load_paldb_index_map(GAME_INPUT / "feature-indexes", "shard3", 1)
    keys = [k for k, _ in imap.key_items() if k != INTERCEPT_KEY][:6]
    rng = np.random.default_rng(1)
    records = []
    for i in range(60):
        feats = []
        for k in rng.choice(len(keys), size=3, replace=False):
            name, term = split_key(keys[int(k)])
            feats.append({"name": name, "term": term,
                          "value": float(rng.normal())})
        records.append({"uid": f"u{i}", "label": float(rng.integers(0, 2)),
                        "features": feats, "weight": None, "offset": None,
                        "metadataMap": None})
    data_dir = tmp_path / "train"
    data_dir.mkdir()
    write_container(data_dir / "part-0.avro", schemas.TRAINING_EXAMPLE,
                    records)

    out = glm_run([
        "--training-data-directory", str(data_dir),
        "--output-directory", str(tmp_path / "out"),
        "--task", "LOGISTIC_REGRESSION",
        "--offheap-indexmap-dir", str(GAME_INPUT / "feature-indexes"),
        "--offheap-indexmap-namespace", "shard3",
        "--regularization-weights", "1.0",
        "--max-num-iterations", "15",
    ])
    assert out["numRows"] == 60
    # The model text lists coefficients in the PalDB store's 31-feature
    # index space (intercept included).
    model_txt = (tmp_path / "out" / "best-model" / "model.txt").read_text()
    assert "(INTERCEPT)" in model_txt


# ---------------------------------------------------------------------------
# Writer (VERDICT r3 missing #1): write -> read round trip + layout parity
# with the reference's own fixture structure.
# ---------------------------------------------------------------------------


def test_write_store_round_trips(tmp_path):
    from photon_ml_tpu.data.paldb import write_paldb_store

    pairs = [("a\x01t", 0), (0, "a\x01t"), ("b\x01", 1), (1, "b\x01"),
             ("long-feature-name\x01with-term", 300),
             (300, "long-feature-name\x01with-term"),
             ("i9", 9), (9, "i9"), ("i255", 255), (255, "i255"),
             ("unicode-é中", 70000), (70000, "unicode-é中")]
    path = tmp_path / "paldb-partition-t-0.dat"
    write_paldb_store(path, pairs)
    got = dict(read_paldb_store(path))
    assert got == dict(pairs)


def test_write_store_multibyte_offsets(tmp_path):
    """Enough entries in one key-length class that data offsets need
    multi-byte varints (the slot size grows accordingly)."""
    from photon_ml_tpu.data.paldb import write_paldb_store

    pairs = [(f"f{i:04d}\x01term-{i:04d}", i) for i in range(2000)]
    path = tmp_path / "big.dat"
    write_paldb_store(path, pairs)
    got = dict(read_paldb_store(path))
    assert len(got) == 2000
    assert got["f1999\x01term-1999"] == 1999


def test_write_store_rejects_duplicates_allows_empty(tmp_path):
    from photon_ml_tpu.data.paldb import write_paldb_store

    with pytest.raises(ValueError, match="duplicate"):
        write_paldb_store(tmp_path / "d.dat", [("a", 1), ("a", 2)])
    # An empty store is legal — hash partitions can be empty and the
    # 0..N-1 filename scan still needs the file to exist.
    write_paldb_store(tmp_path / "e.dat", [])
    assert list(read_paldb_store(tmp_path / "e.dat")) == []


@pytest.mark.parametrize("num_partitions", [1, 3])
def test_build_index_stores_round_trip(tmp_path, num_partitions):
    from photon_ml_tpu.data.paldb import build_paldb_index_stores

    names = [feature_key(f"name{i}", f"t{i % 4}") for i in range(50)]
    names.append(INTERCEPT_KEY)
    written = build_paldb_index_stores(tmp_path, "myShard", names,
                                       num_partitions=num_partitions)
    loaded = load_paldb_index_map(tmp_path, "myShard", num_partitions)
    assert dict(written.key_items()) == dict(loaded.key_items())
    assert sorted(i for _, i in loaded.key_items()) == list(range(len(names)))


def test_written_store_layout_matches_fixture_structure(tmp_path):
    """Re-write the reference fixture's CONTENT with our writer and
    compare the container structure field by field: same sections (key
    lengths, counts), same slot counts (Math.round(count/0.75)), same
    slot sizes, same empty-slot/data-sentinel conventions. Byte identity
    is not expected (insertion order differs), but every structural
    header field the PalDB 1.1 reader navigates by must match."""
    import struct as st

    from photon_ml_tpu.data.paldb import write_paldb_store

    fixture = (Path("/root/reference/photon-ml/src/test/resources/"
                    "PalDBIndexMapTest/paldb_offheapmap_for_heart") /
               "paldb-partition-global-0.dat")

    def header_fields(path):
        raw = Path(path).read_bytes()
        n_magic = st.unpack_from(">H", raw, 0)[0]
        o = 2 + n_magic + 8
        key_count, klc, mkl = st.unpack_from(">iii", raw, o)
        o += 12
        secs = []
        for _ in range(klc):
            klen, kcnt, slots, ssize, _io = st.unpack_from(">iiiii", raw, o)
            o += 28
            secs.append((klen, kcnt, slots, ssize))
        return key_count, mkl, secs

    pairs = list(read_paldb_store(fixture))
    ours = tmp_path / "rewrite.dat"
    write_paldb_store(ours, pairs)

    ref_kc, ref_mkl, ref_secs = header_fields(fixture)
    our_kc, our_mkl, our_secs = header_fields(ours)
    assert our_kc == ref_kc
    assert our_mkl == ref_mkl
    assert our_secs == ref_secs
    # And the rewrite round-trips to identical content.
    assert dict(read_paldb_store(ours)) == dict(pairs)


def test_slot_hash_matches_fixture_placement():
    """The writer's murmur3(seed 42) slot hash reproduces the placement
    observed in the reference's own stores: every key sits at its hash
    slot or within linear-probe distance of it."""
    import struct as st

    from photon_ml_tpu.data.paldb import (
        _MAGIC,
        _murmur3_32,
        _unpack_varint,
    )

    fixture = GAME_INPUT / "feature-indexes" / "paldb-partition-shard1-0.dat"
    raw = fixture.read_bytes()
    n_magic = st.unpack_from(">H", raw, 0)[0]
    assert raw[2:2 + n_magic].decode() == _MAGIC
    o = 2 + n_magic + 8
    key_count, klc, _ = st.unpack_from(">iii", raw, o)
    o += 12
    secs = []
    for _ in range(klc):
        klen, kcnt, slots, ssize, ioff = st.unpack_from(">iiiii", raw, o)
        o += 28
        secs.append((klen, kcnt, slots, ssize, ioff))
    o += 4
    index_start = st.unpack_from(">i", raw, o)[0]

    exact = probed = 0
    for klen, kcnt, slots, ssize, ioff in secs:
        base = index_start + ioff
        occupancy = kcnt / slots
        for s in range(slots):
            slot = raw[base + s * ssize: base + (s + 1) * ssize]
            if _unpack_varint(slot, klen)[0] == 0:
                continue
            h = _murmur3_32(bytes(slot[:klen])) % slots
            dist = (s - h) % slots
            if dist == 0:
                exact += 1
            else:
                probed += 1
                assert dist <= kcnt, "key unreachable by linear probing"
    assert exact + probed == key_count
    # The hash must explain the bulk of placements directly (collisions
    # at 0.75 load factor account for the rest).
    assert exact / key_count > 0.5
