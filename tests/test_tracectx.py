"""Request-scoped tracing (photon_ml_tpu/telemetry/tracectx.py), the
exemplar plumbing, the executable profiler, and the divergence watchdog:
context propagation across the front-end's thread hops (solo-retry keeps
its original trace_id), tail-sampling classes, /tracez + exemplar
rendering under concurrent mutation, and the watchdog-triggered flight
dump contents."""

import asyncio
import json
import threading
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.optimization.convergence import (
    SolverDivergedError,
    check_solver_finite,
)
from photon_ml_tpu.serving import (
    BucketLadder,
    FrontendConfig,
    RequestRejected,
    ServingFrontend,
)
from photon_ml_tpu.telemetry import ObservabilityServer, mint, trace_tail
from photon_ml_tpu.telemetry.tracectx import NOOP_CONTEXT, TraceTail

from tests.test_exposition import parse_prometheus
from tests.test_serving_frontend import (
    DT,
    LADDER,
    _dataset,
    _game_model,
    _singles,
)


@pytest.fixture
def sampling():
    """Telemetry + trace sampling on, everything clean before/after."""
    telemetry.reset()
    telemetry.enable()
    try:
        yield
    finally:
        telemetry.disable()
        telemetry.reset()


# -- context + tail unit semantics -----------------------------------------

def test_mint_disabled_returns_shared_noop():
    telemetry.disable()
    ctx = mint("request")
    assert ctx is NOOP_CONTEXT and ctx is mint("solve")
    assert ctx.trace_id is None
    ctx.event("x")
    ctx.annotate(a=1)
    ctx.finish("error")  # must not reach the tail
    assert trace_tail().snapshot()["seen"] == 0


def test_context_timeline_and_tail_classes(sampling):
    tail = TraceTail(floor_every=4, slow_capacity=8, error_capacity=8,
                     floor_capacity=8)
    # error outcomes always keep, with ordered timelines + annotations
    ctx = mint("request")
    ctx.event("admit")
    ctx.annotate(model="m")
    ctx.finish("shed")
    # finish() reported to the PROCESS tail; replay the snapshot into
    # the local one to test classification deterministically
    assert ctx.outcome == "shed" and ctx.duration_s >= 0
    tail.record(ctx)
    snap = tail.snapshot()
    assert snap["kept"]["error"] == 1
    kept = snap["traces"]["error"][0]
    assert kept["trace_id"] == ctx.trace_id
    assert kept["annotations"] == {"model": "m"}
    assert [e["stage"] for e in kept["events"]] == ["admit"]
    # stamped stages merge into the timeline, time-ordered
    ctx2 = mint("request")
    ctx2.event("admit")
    import time as _t

    t_co, t_set = _t.perf_counter(), _t.perf_counter()
    ctx2.finish("ok", stages={"coalesce": t_co, "settle": t_set})
    found = trace_tail().find(ctx2.trace_id)
    assert found is not None
    stages = [e["stage"] for e in found["events"]]
    assert stages == ["admit", "coalesce", "settle"]
    # double-finish is idempotent
    seen = trace_tail().snapshot()["seen"]
    ctx2.finish("error")
    assert trace_tail().snapshot()["seen"] == seen


def test_tail_slow_decile_and_floor(sampling):
    tail = TraceTail(floor_every=10, window=200)

    def fake(duration, outcome="ok"):
        ctx = telemetry.TraceContext("request")
        ctx.outcome = outcome
        ctx.duration_s = duration
        return ctx

    # 200 spread-out fast durations + sprinkled 1.0s outliers: after
    # the window warms, the outliers land in the slow ring and sub-
    # threshold traces land (every 10th) in the floor ring. Durations
    # VARY (real traffic never produces byte-equal wall times) — with
    # all-equal durations the inclusive p90 threshold would classify
    # everything slow, which the bounded rings absorb by design.
    rng = np.random.default_rng(0)
    for i in range(200):
        tail.record(fake(1.0 if i % 50 == 49
                         else 0.001 * (1 + rng.random())))
    snap = tail.snapshot()
    assert snap["slow_threshold_s"] is not None
    assert snap["slow_threshold_s"] <= 1.0
    slow_durs = [t["duration_s"] for t in snap["traces"]["slow"]]
    assert 1.0 in slow_durs
    assert snap["kept"]["floor"] >= 1
    # floor entries are ordinary fast traces
    assert all(t["duration_s"] <= snap["slow_threshold_s"]
               for t in snap["traces"]["floor"])
    # errors keep regardless of speed
    tail.record(fake(0.0001, outcome="error"))
    assert tail.snapshot()["kept"]["error"] == 1


def test_event_cap_bounds_runaway_timelines(sampling):
    ctx = mint("solve")
    for i in range(2 * ctx.MAX_EVENTS):
        ctx.event("solver_step")
    assert len(ctx.events) == ctx.MAX_EVENTS
    ctx.finish("ok")
    found = trace_tail().find(ctx.trace_id)
    if found is not None:  # kept (first traces always qualify as slow)
        assert found["events_dropped"] is True


# -- front-end propagation -------------------------------------------------

@pytest.fixture
def traced_frontend(rng, sampling):
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    fe = ServingFrontend({"default": gm}, dtype=DT,
                         ladder=BucketLadder(**LADDER),
                         config=FrontendConfig(coalesce_window_s=0.05,
                                               max_pending=256))
    return fe, gm


@pytest.mark.needs_f64
def test_request_timeline_spans_admission_to_settle(traced_frontend):
    """One coalesced window: every request's context crosses the event
    loop -> dispatch-executor -> scatter hops with the full
    admit -> coalesce -> dispatch -> settle timeline, and the latency
    histogram's buckets carry resolvable trace_id exemplars."""
    fe, gm = traced_frontend
    reqs = _singles(500, 8)
    ctxs = [mint("request") for _ in reqs]

    async def run():
        async with fe:
            return await asyncio.gather(
                *[fe.score(r, trace=c) for r, c in zip(reqs, ctxs)])

    out = asyncio.run(run())
    for r, o in zip(reqs, out):
        np.testing.assert_allclose(o, gm.score(r), rtol=1e-10, atol=1e-10)
    for ctx in ctxs:
        assert ctx.outcome == "ok"
        stages = [s for s, _ in sorted(ctx.events, key=lambda e: e[1])]
        assert stages[0] == "admit" and stages[-1] == "settle"
        assert "coalesce" in stages and "dispatch" in stages
    # every latency exemplar resolves to a kept /tracez timeline OR was
    # dropped by the tail — but at least one bucket carries an exemplar
    # from THIS run's ids
    ex = telemetry.histogram(
        "serving.frontend.request_latency_seconds").exemplars()
    assert ex, "no latency bucket carries an exemplar"
    ids = {c.trace_id for c in ctxs}
    assert any(tid in ids for tid, _, _ in ex.values())


@pytest.mark.needs_f64
def test_solo_retry_keeps_original_trace_id(traced_frontend):
    """Fault isolation re-scores a poisoned window per-request: each
    retried request must keep its ORIGINAL context (same trace_id, one
    timeline) with the retry_solo hop recorded."""
    import scipy.sparse as sp

    from photon_ml_tpu.data.game_data import GameDataset

    fe, gm = traced_frontend
    good = _singles(600, 4)
    bad = GameDataset.build(
        responses=np.zeros(1),
        feature_shards={"global": sp.csr_matrix(np.ones((1, 6)))},
        ids={})  # missing 'user' shard and id columns
    ctxs = [mint("request") for _ in range(5)]

    async def run():
        async with fe:
            tasks = [asyncio.ensure_future(fe.score(r, trace=c))
                     for r, c in zip(good[:2] + [bad] + good[2:], ctxs)]
            return await asyncio.gather(*tasks, return_exceptions=True)

    out = asyncio.run(run())
    assert isinstance(out[2], KeyError)
    assert fe.stats()["isolation_splits"] == 1
    good_ctxs = ctxs[:2] + ctxs[3:]
    for ctx in good_ctxs:
        assert ctx.outcome == "ok"
        stages = [s for s, _ in ctx.events]
        assert "retry_solo" in stages and "admit" in stages
    # the offender: SAME context object finished as error, tail-kept
    bad_ctx = ctxs[2]
    assert bad_ctx.outcome == "error"
    assert bad_ctx.annotations["error"] == "KeyError"
    found = trace_tail().find(bad_ctx.trace_id)
    assert found is not None and found["outcome"] == "error"
    assert "retry_solo" in [e["stage"] for e in found["events"]]


@pytest.mark.needs_f64
def test_shed_keeps_timeline_and_tags_rejection(traced_frontend):
    """Every shed keeps its trace: the typed RequestRejected carries the
    trace_id and /tracez resolves it."""
    fe, _ = traced_frontend
    fe.config = FrontendConfig(coalesce_window_s=0.2, max_pending=1)
    reqs = _singles(700, 3)

    async def run():
        async with fe:
            return await asyncio.gather(
                *[fe.score(r) for r in reqs], return_exceptions=True)

    out = asyncio.run(run())
    sheds = [e for e in out if isinstance(e, RequestRejected)]
    assert sheds, "max_pending=1 must shed concurrent submissions"
    for e in sheds:
        assert e.trace_id is not None
        found = trace_tail().find(e.trace_id)
        assert found is not None
        assert found["outcome"] == "shed"
        assert found["annotations"]["scope"] == "process"


@pytest.mark.needs_f64
def test_deferred_path_keeps_timelines_and_resolvable_exemplars(
        traced_frontend):
    """The default (no explicit trace=) hot path defers trace
    materialization to the batched group settle: kept timelines still
    carry admit -> coalesce -> dispatch -> settle, and every latency
    exemplar stamped on the histogram RESOLVES against /tracez (ids
    mint only for kept traces)."""
    fe, _ = traced_frontend
    reqs = _singles(900, 24)
    _, info = fe.replay(reqs, concurrency=8)
    assert info["shed"] == 0 and info["errors"] == 0
    snap = trace_tail().snapshot()
    assert snap["seen"] == len(reqs)
    kept = snap["traces"]["slow"] + snap["traces"]["floor"]
    assert kept, "tail kept nothing from a 24-request replay"
    for tr in kept:
        stages = [e["stage"] for e in tr["events"]]
        assert stages[0] == "admit" and stages[-1] == "settle"
        assert "coalesce" in stages and "dispatch" in stages
        assert tr["start_unix"] is not None
    ex = telemetry.histogram(
        "serving.frontend.request_latency_seconds").exemplars()
    assert ex, "no exemplar stamped"
    for tid, _, _ in ex.values():
        assert trace_tail().find(tid) is not None, \
            "exemplar must resolve to a kept /tracez timeline"


# -- /tracez + exemplars under concurrent mutation -------------------------

def test_tracez_and_exemplars_under_concurrent_scrape(sampling):
    """Scrape-during-load (the PR 9 exposition discipline): /metrics
    (with exemplars) and /tracez stay well-formed while worker threads
    hammer observations and trace finishes."""
    h = telemetry.histogram("load.request_latency_seconds",
                            exemplars=True)
    stop = threading.Event()

    def worker(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            ctx = mint("request")
            ctx.event("admit")
            v = float(rng.random() * 0.01)
            h.observe(v, exemplar=ctx.trace_id)
            ctx.finish("ok" if rng.random() > 0.1 else "error")

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(3)]
    with ObservabilityServer(port=0) as srv:
        for t in threads:
            t.start()
        try:
            for i in range(20):
                # Alternate plain 0.0.4 and negotiated OpenMetrics
                # scrapes: exemplar syntax is ILLEGAL in 0.0.4, so the
                # plain render must stay exemplar-free while the
                # Accept-negotiated one carries them + '# EOF'.
                req = urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/metrics",
                    headers=({"Accept": "application/openmetrics-text"}
                             if i % 2 else {}))
                resp = urllib.request.urlopen(req, timeout=5)
                text = resp.read().decode()
                if i % 2:
                    assert resp.headers["Content-Type"].startswith(
                        "application/openmetrics-text")
                    assert text.endswith("# EOF\n")
                else:
                    assert " # {" not in text, \
                        "exemplar leaked into a text-0.0.4 scrape"
                fams = parse_prometheus(text)  # oracle: monotone + +Inf
                tz = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/tracez",
                    timeout=5).read())
                # every kept trace is structurally complete
                for ring in tz["traces"].values():
                    for tr in ring:
                        assert tr["trace_id"].startswith("t")
                        assert tr["outcome"] in ("ok", "error")
                        assert tr["duration_s"] >= 0
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
    fam = fams["load_request_latency_seconds"]
    assert fam["exemplars"], "no exemplar rendered under load"
    for sample, labels, ex in fam["exemplars"]:
        assert sample == "load_request_latency_seconds_bucket"
        assert "le" in labels
        assert ex["labels"]["trace_id"].startswith("t")
    tzsnap = trace_tail().snapshot()
    assert tzsnap["seen"] > 0
    assert tzsnap["kept"]["error"] > 0


# -- divergence watchdog ---------------------------------------------------

def test_check_solver_finite_passes_and_raises(sampling):
    check_solver_finite("streaming-lbfgs", 3, 1.0, 0.5, None)  # no-op
    ctx = mint("solve")
    with pytest.raises(SolverDivergedError) as ei:
        check_solver_finite("streaming-lbfgs", 7, float("nan"), 1.0, ctx)
    e = ei.value
    assert e.solver == "streaming-lbfgs" and e.iteration == 7
    assert e.trace_id == ctx.trace_id
    assert "diverged at outer iteration 7" in str(e)
    # the solve's context finished as diverged and is tail-kept
    found = trace_tail().find(ctx.trace_id)
    assert found is not None and found["outcome"] == "diverged"
    assert found["annotations"]["iteration"] == 7
    with pytest.raises(SolverDivergedError):
        check_solver_finite("streaming-tron", 1, 0.0, float("inf"))


def test_watchdog_triggers_trace_tagged_flight_dump(tmp_path, rng):
    """Driver-level: NaN training data diverges the streamed solve; the
    typed SolverDivergedError triggers the fault flight dump, tagged
    with the solve's trace_id, whose traces block holds the diverged
    timeline (ISSUE 11 satellite acceptance)."""
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    train = tmp_path / "train"
    train.mkdir(parents=True)
    records = []
    for i in range(96):
        vals = rng.normal(0, 1, 3)
        records.append({
            "uid": f"u{i}",
            "label": float(i % 2),
            "features": [
                {"name": f"f{j}", "term": None,
                 # poison one row: a NaN feature value NaNs the margins
                 "value": (float("nan") if i == 17 and j == 0
                           else float(v))}
                for j, v in enumerate(vals)],
            "weight": None, "offset": None, "metadataMap": None})
    write_container(train / "part-00000.avro",
                    schemas.TRAINING_EXAMPLE, records)
    out = tmp_path / "diverged"
    with pytest.raises(SolverDivergedError) as ei:
        game_training_driver.run([
            "--train-input-dirs", str(train),
            "--output-dir", str(out),
            "--task-type", "LOGISTIC_REGRESSION",
            "--fixed-effect-data-configurations", "fixed:global",
            "--fixed-effect-optimization-configurations",
            "fixed:10,1e-7,1.0,1.0,LBFGS,L2",
            "--updating-sequence", "fixed",
            "--stream-train", "--batch-rows", "32",
            "--hbm-budget", "64M", "--feeder", "python",
        ])
    e = ei.value
    assert e.solver == "streaming-lbfgs" and e.trace_id is not None
    flight = json.loads((out / "flight.json").read_text())
    fl = flight["flight"]
    assert fl["reason"] == "fault:SolverDivergedError"
    assert fl["trace_id"] == e.trace_id
    # the diverged solve's timeline is stamped into the dump
    errors = fl["traces"]["traces"]["error"]
    diverged = [t for t in errors if t["trace_id"] == e.trace_id]
    assert len(diverged) == 1
    assert diverged[0]["outcome"] == "diverged"
    assert diverged[0]["annotations"]["coordinate"] == "fixed"
    assert diverged[0]["annotations"]["solver"] == "streaming-lbfgs"


# -- executable profiler ---------------------------------------------------

@pytest.mark.needs_f64
def test_profiler_build_and_dispatch_table(traced_frontend):
    """The cache profiler records per-key lower/first-call wall + cost
    analysis at build and per-bucket dispatch-to-settle timings, and
    the table rides in frontend stats (-> /statusz, metrics.json)."""
    fe, _ = traced_frontend
    reqs = _singles(800, 12)
    results, info = fe.replay(reqs, concurrency=4)
    assert info["errors"] == 0
    table = fe.stats()["cache"]["profiler"]
    assert table["builds"], "no build was profiled"
    for entry in table["builds"].values():
        assert entry["lower_s"] is not None and entry["lower_s"] > 0
        assert entry["first_call_s"] is not None
        # CPU backend reports static FLOPs for these kernels
        assert entry.get("flops", 0) >= 0
    assert table["dispatch"], "no dispatch was profiled"
    for row in table["dispatch"].values():
        assert row["dispatches"] >= 1
        assert row["mean_s"] > 0
        assert row["min_s"] <= row["mean_s"] <= row["max_s"]
    # per-bucket registry histograms observed dispatches
    snap = telemetry.snapshot()["histograms"]
    bucket_hists = [k for k in snap
                    if k.startswith("serving.bucket.r")
                    and k.endswith(".dispatch_seconds")]
    assert bucket_hists
    assert sum(snap[k]["count"] for k in bucket_hists) \
        == sum(r["dispatches"] for r in table["dispatch"].values())
    # profiling did not defeat the compile-count discipline
    fe.cache.assert_max_retraces(per_fn=1)
    assert fe.cache.total_traces() == fe.cache.compilations
