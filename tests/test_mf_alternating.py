"""Streamed MF alternating-least-squares tests (ops/mf_alternating.py +
algorithm StreamingFactoredRandomEffectCoordinate): out-of-core factor
tables with model bytes independent of residency/feeder config,
parity-bounded against the in-core FactoredRandomEffectCoordinate, and
typed divergence faults."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

from photon_ml_tpu.algorithm import (
    FactoredRandomEffectCoordinate,
    StreamingFactoredRandomEffectCoordinate,
)
from photon_ml_tpu.data.factor_cache import (
    DeviceFactorCache,
    plan_factors,
)
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.models import FactoredRandomEffectModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.mf_alternating import StreamedMFObjective
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    MFOptimizationConfiguration,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optimization.convergence import SolverDivergedError
from photon_ml_tpu.types import TaskType

_L2 = RegularizationContext(RegularizationType.L2)


def _glm_cfg(**kw):
    kwargs = dict(max_iterations=25, tolerance=1e-8,
                  regularization_weight=1e-3, regularization_context=_L2)
    kwargs.update(kw)
    return GLMOptimizationConfiguration(**kwargs)


def _problem(rng, n=400, d=10, n_users=12, k_true=2, noise=0.05):
    x = rng.normal(0, 1, (n, d))
    users = rng.integers(0, n_users, n)
    coefs = rng.normal(0, 1.0, (n_users, k_true)) \
        @ rng.normal(0, 1, (k_true, d))
    y = np.einsum("nd,nd->n", x, coefs[users]) + rng.normal(0, noise, n)
    names = np.asarray([f"u{u:02d}" for u in users])
    return x, y, names


def _batches(x, y, names, rows=96):
    out = []
    for a in range(0, len(y), rows):
        b = min(a + rows, len(y))
        out.append(GameDataset.build(
            responses=y[a:b],
            feature_shards={"s": sp.csr_matrix(x[a:b])},
            ids={"userId": names[a:b]}))
    return out


def _coord(x, y, names, rows=96, **kw):
    kwargs = dict(
        name="mf", make_stream=lambda: iter(_batches(x, y, names, rows)),
        feature_shard_id="s", random_effect_type="userId",
        task_type=TaskType.LINEAR_REGRESSION,
        config=_glm_cfg(), latent_config=_glm_cfg(),
        mf_config=MFOptimizationConfiguration(max_iterations=2,
                                              num_factors=2),
        entities_per_shard=5)
    kwargs.update(kw)
    return StreamingFactoredRandomEffectCoordinate(**kwargs)


def _model_bytes(m):
    return (b"".join(np.asarray(c).tobytes()
                     for c in m.latent.local_coefs)
            + np.asarray(m.projection_matrix).tobytes())


def test_streamed_mf_learns_low_rank_structure(rng):
    x, y, names = _problem(rng)
    coord = _coord(x, y, names)
    model = coord.initialize_model()
    assert isinstance(model, FactoredRandomEffectModel)
    s0 = np.asarray(coord.score(model))
    model, trackers = coord.solve(model)
    s1 = np.asarray(coord.score(model))
    assert len(trackers) == 2  # one OptimizerResult per sweep
    loss0 = float(np.mean((s0 - y) ** 2))
    loss1 = float(np.mean((s1 - y) ** 2))
    assert loss1 < 0.1 * loss0, (loss0, loss1)
    # model assembly: true entity counts, codes into the plan vocab
    assert model.latent.num_entities == len(set(names))
    assert model.projection_matrix.shape == (2, x.shape[1])


def test_streamed_parity_bounded_vs_in_core(rng):
    """Same data, same iteration counts, same seeded B0: the streamed
    ALS (exact ridge gamma solves + streamed L-BFGS refit) and the
    in-core coordinate (vmapped L-BFGS gamma solves + fused refit)
    converge to the same strictly convex alternating optimum — scores
    agree to a tight relative bound."""
    x, y, names = _problem(rng)
    coord = _coord(x, y, names)
    model, _ = coord.solve()
    s_stream = np.asarray(coord.score(model))

    data = GameDataset.build(
        responses=y, feature_shards={"s": sp.csr_matrix(x)},
        ids={"userId": names})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "s",
                                            projector_type="IDENTITY"))
    in_core = FactoredRandomEffectCoordinate(
        name="mf", dataset=ds, task_type=TaskType.LINEAR_REGRESSION,
        config=_glm_cfg(), latent_config=_glm_cfg(),
        mf_config=MFOptimizationConfiguration(max_iterations=2,
                                              num_factors=2))
    icm, _ = in_core.update_model(in_core.initialize_model(), None,
                                  jax.random.key(0))
    s_core = np.asarray(in_core.score(icm))
    scale = np.max(np.abs(s_core))
    assert np.max(np.abs(s_stream - s_core)) <= 1e-3 * scale, \
        np.max(np.abs(s_stream - s_core)) / scale
    # and the streamed host-scoring path agrees with the coordinate's
    np.testing.assert_allclose(model.score_numpy(data), s_stream,
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_model_bytes_identical_across_residency_and_batching(rng):
    """The tentpole acceptance: a factor table larger than the budget
    trains out-of-core with model bytes IDENTICAL to the fully
    resident run (f32 spill bitwise), for eviction-forced budgets and
    across batch cuts that straddle bucket boundaries."""
    x, y, names = _problem(rng)
    base, _ = _coord(x, y, names).solve()

    tight = _coord(x, y, names, hbm_budget_bytes=48)
    m_tight, _ = tight.solve()
    st = tight.cache.stats()
    assert st["evictions"] > 0 and st["misses"] > 0
    # the factor table exceeds the budget — out-of-core by construction
    total_factor_bytes = sum(4 * s.e_pad * 2 for s in tight.plan.shards)
    assert total_factor_bytes > 48
    assert _model_bytes(m_tight) == _model_bytes(base)

    tiny = _coord(x, y, names, hbm_budget_bytes=1)
    m_tiny, _ = tiny.solve()
    assert _model_bytes(m_tiny) == _model_bytes(base)


def test_model_bytes_identical_across_stream_batch_rows(rng):
    """Different --batch-rows cuts re-bucket the OBSERVATION stream.

    The gamma normal equations accumulate per batch in f32, so the cut
    changes the summation association — bytes are not bitwise across
    batch sizes (same as the sharded GLM fold vs the one-shot path) —
    but the solve must stay deterministic per cut and parity-close
    across cuts."""
    x, y, names = _problem(rng)
    a1, _ = _coord(x, y, names, rows=96).solve()
    a2, _ = _coord(x, y, names, rows=96).solve()
    assert _model_bytes(a1) == _model_bytes(a2)  # per-cut determinism
    b1, _ = _coord(x, y, names, rows=57).solve()
    np.testing.assert_allclose(
        np.asarray(a1.projection_matrix), np.asarray(b1.projection_matrix),
        rtol=1e-3, atol=1e-4)


def test_bf16_factors_residency_independent_and_parity_bounded(rng):
    x, y, names = _problem(rng)
    base, _ = _coord(x, y, names).solve()
    resident = _coord(x, y, names, hbm_budget_bytes=10 ** 9,
                      spill_dtype="bf16")
    m_res, _ = resident.solve()
    evicting = _coord(x, y, names, hbm_budget_bytes=48,
                      spill_dtype="bf16")
    m_ev, _ = evicting.solve()
    assert evicting.cache.stats()["evictions"] > 0
    assert resident.cache.stats()["evictions"] == 0
    # two budgets with totally different eviction pressure: same bytes
    assert _model_bytes(m_res) == _model_bytes(m_ev)
    # quantized models differ from f32 only within the bf16 bound
    assert _model_bytes(m_res) != _model_bytes(base)
    b_f32 = np.asarray(base.projection_matrix)
    b_bf = np.asarray(m_res.projection_matrix)
    assert np.max(np.abs(b_bf - b_f32)) <= 0.05 * np.max(np.abs(b_f32))


def test_redecode_tier_bitwise_and_no_host_bytes(rng):
    """redecode factors: evicted shards keep NO host copy; misses
    re-derive from re-decoded observations bit-for-bit the buffer-tier
    bytes (the gamma solve is a pure function of (observations, B))."""
    x, y, names = _problem(rng)
    buf = _coord(x, y, names, hbm_budget_bytes=48)
    m_buf, _ = buf.solve()
    rd = _coord(x, y, names, hbm_budget_bytes=48,
                spill_source="redecode")
    m_rd, _ = rd.solve()
    st = rd.cache.stats()
    assert st["redecodes"] > 0
    assert st["spill_bytes_host"] == 0
    assert _model_bytes(m_rd) == _model_bytes(m_buf)


def test_feeder_variant_streams_identical_bytes(rng):
    """Any deterministic replayable stream with the same batch cuts
    writes the same bytes — the coordinate-level analog of the CLI's
    native-vs-python feeder identity (pinned end-to-end in
    tests/test_cli_drivers.py)."""
    x, y, names = _problem(rng)
    a, _ = _coord(x, y, names).solve()

    def generator_stream():
        # a lazy generator instead of a list iterator: different
        # producer, same batches
        for ds in _batches(x, y, names, 96):
            yield ds

    b, _ = _coord(x, y, names, make_stream=generator_stream).solve()
    assert _model_bytes(a) == _model_bytes(b)


def test_residual_scores_shift_solution_and_fold_into_offsets(rng):
    """The coordinate-descent residual contract: residual scores act as
    extra offsets in BOTH half-steps, and clearing them restores the
    base solution bitwise."""
    x, y, names = _problem(rng)
    coord = _coord(x, y, names)
    base, _ = coord.solve()
    res = np.linspace(-2.0, 2.0, len(y)).astype(np.float32)
    shifted, _ = coord.solve(residual_scores=res)
    assert _model_bytes(shifted) != _model_bytes(base)
    again, _ = coord.solve(residual_scores=None)
    assert _model_bytes(again) == _model_bytes(base)
    # residual-as-offset equivalence: solving against residual r is the
    # same objective as training on labels y - r (both half-steps see
    # t = y - off - r), so the two solutions agree to fp association
    direct = _coord(x, y - np.asarray(res, np.float64), names)
    m_direct, _ = direct.solve()
    np.testing.assert_allclose(
        np.asarray(shifted.projection_matrix),
        np.asarray(m_direct.projection_matrix), rtol=1e-3, atol=1e-4)


def test_zero_observation_entities_solve_to_zero(rng):
    """Entities planned but never observed (e.g. from a stale vocab)
    get exactly-zero factors — the ridge normal equations with
    A = 0, b = 0 — and survive the whole pipeline."""
    x, y, names = _problem(rng, n=200, n_users=6)
    vocab = np.asarray(sorted(set(names) | {"zz-never-seen-1",
                                            "zz-never-seen-2"}))
    counts = np.asarray([int((names == v).sum()) for v in vocab])
    assert (counts == 0).sum() == 2
    plan = plan_factors(vocab, counts, entities_per_shard=4)
    cache = DeviceFactorCache(plan, 2)
    obj = StreamedMFObjective(
        lambda: iter(_batches(x, y, names, 96)), "s", "userId", plan,
        cache, x.shape[1], loss_for_task(TaskType.LINEAR_REGRESSION))
    b0 = rng.normal(0, 0.5, (2, x.shape[1])).astype(np.float32)
    obj.gamma_pass(b0, 1e-3)
    for name in ("zz-never-seen-1", "zz-never-seen-2"):
        code = int(np.flatnonzero(vocab == name)[0])
        shard = int(plan.shard_of_code[code])
        slot = int(plan.slot_of_code[code])
        g = np.asarray(cache.ensure(shard))
        assert np.all(g[slot] == 0.0)


@pytest.mark.slow
def test_entity_counts_straddling_bucket_boundaries(rng):
    """Entity populations at/over the pow-2 pad and shard-split
    boundaries train and keep byte-identity across residency."""
    for n_users in (4, 5, 8, 9):
        x, y, names = _problem(rng, n=260, n_users=n_users)
        a, _ = _coord(x, y, names, entities_per_shard=4).solve()
        b, _ = _coord(x, y, names, entities_per_shard=4,
                      hbm_budget_bytes=32).solve()
        assert _model_bytes(a) == _model_bytes(b), n_users
        assert a.latent.num_entities == len(set(names))


def test_unknown_entities_at_scoring_time_after_streamed_train(rng):
    """A streamed-MF-trained model scores datasets containing unknown
    entities with ZERO contribution for them — via the host model path
    AND the serving engine (the PR-2 unknown-entity join semantics)."""
    x, y, names = _problem(rng)
    coord = _coord(x, y, names)
    model, _ = coord.solve()

    x_new = rng.normal(0, 1, (4, x.shape[1]))
    mixed = GameDataset.build(
        responses=np.zeros(4),
        feature_shards={"s": sp.csr_matrix(x_new)},
        ids={"userId": np.asarray([names[0], "brand-new-entity",
                                   names[1], "another-new-one"])})
    host = np.asarray(model.score_numpy(mixed))
    assert host[1] == 0.0 and host[3] == 0.0
    assert host[0] != 0.0 and host[2] != 0.0

    from photon_ml_tpu.models.game_model import GameModel
    from photon_ml_tpu.serving import StreamingGameScorer

    engine = StreamingGameScorer(
        GameModel({"mf": model}, TaskType.LINEAR_REGRESSION))
    dev = np.asarray(engine.score(mixed))
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


def test_divergence_watchdog_raises_typed_error(rng):
    """A NaN observation poisons the alternating solve: the per-sweep
    watchdog (check_solver_finite, shared with the streamed L-BFGS/TRON
    paths) raises a typed SolverDivergedError instead of silently
    writing a NaN model."""
    x, y, names = _problem(rng)
    y_bad = y.copy()
    y_bad[7] = np.nan
    coord = _coord(x, y_bad, names)
    with pytest.raises(SolverDivergedError) as ei:
        coord.solve()
    assert ei.value.iteration >= 0
    assert not np.isfinite(ei.value.value) \
        or not np.isfinite(ei.value.grad_norm)


def test_compile_counts_bounded_by_buckets_and_shared_across_grid(
        rng, tracing_guard):
    """Compile discipline: kernel traces stay within the
    observed-geometry budgets (bucket counts, never entity counts), a
    λ-grid point sharing the objective adds NO new traces, and a
    DIFFERENT entity population with the same bucket shapes reuses the
    same executables."""
    x, y, names = _problem(rng)
    coord = _coord(x, y, names, tracing_guard=tracing_guard)
    coord.solve()
    obj = coord.mf_objective
    obj.assert_trace_budget()
    counts_after_first = dict(obj.guard.counts())

    # λ-grid sharing: a second coordinate over the SAME objective (the
    # driver's grid loop) must not retrace anything.
    coord2 = _coord(x, y, names,
                    config=_glm_cfg(regularization_weight=0.1),
                    latent_config=_glm_cfg(regularization_weight=0.1),
                    mf_objective=obj)
    coord2.solve()
    assert obj.guard.counts() == counts_after_first
    obj.assert_trace_budget()
    for name, budget in obj.trace_budgets().items():
        tracing_guard.set_budget  # fixture verifies at teardown
        assert obj.guard.counts().get(name, 0) <= budget


def test_scope_enforcement_errors(rng):
    x, y, names = _problem(rng, n=120, n_users=4)

    def make(**kw):
        return _coord(x, y, names, **kw)

    with pytest.raises(ValueError, match="LINEAR_REGRESSION"):
        make(task_type=TaskType.LOGISTIC_REGRESSION)
    with pytest.raises(ValueError, match="L2 only"):
        make(config=_glm_cfg(regularization_context=RegularizationContext(
            RegularizationType.L1)))
    with pytest.raises(ValueError, match="positive gamma L2"):
        make(config=_glm_cfg(regularization_weight=0.0))
    with pytest.raises(ValueError, match="LBFGS"):
        make(latent_config=_glm_cfg(optimizer_type=OptimizerType.TRON))
    with pytest.raises(ValueError, match="down-sampling"):
        make(config=_glm_cfg(down_sampling_rate=0.5))
    # shared-objective k mismatch fails loudly
    base = make()
    with pytest.raises(ValueError, match="num_factors"):
        make(mf_config=MFOptimizationConfiguration(max_iterations=2,
                                                   num_factors=3),
             mf_objective=base.mf_objective)


def test_stream_mutation_fails_loudly(rng):
    """The input changing under the objective (different batch shapes
    between passes) is a hard error, not silent corruption."""
    x, y, names = _problem(rng, n=200, n_users=6)
    calls = {"n": 0}

    def unstable_stream():
        # calls 1-2 are the planning + geometry passes; the cut changes
        # under the objective from the first FEATURE pass on
        calls["n"] += 1
        rows = 96 if calls["n"] <= 2 else 64
        return iter(_batches(x, y, names, rows))

    coord = _coord(x, y, names, make_stream=unstable_stream)
    with pytest.raises(RuntimeError, match="changed under"):
        coord.solve()
