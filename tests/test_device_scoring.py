"""DeviceGameScorer: device-side scoring must match the host numpy path
bit-for-bit (same sums, same unseen-entity zero semantics) across all
sub-model families. Reference scoring semantics:
ml/model/FixedEffectModel.scala:94-105, RandomEffectModel.scala score join,
MatrixFactorizationModel.scala:50-52."""

import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    LogisticRegressionModel,
    MatrixFactorizationModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.device_scoring import DeviceGameScorer
from photon_ml_tpu.types import TaskType


def _dataset(rng, n=80, d=6, n_users=7, n_items=5, user_density=1.0):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    users = rng.integers(0, n_users, n).astype(str)
    items = rng.integers(0, n_items, n).astype(str)
    user_x = sp.csr_matrix(np.hstack(
        [rng.normal(0, 1, (n, 2)), np.ones((n, 1))]))
    return GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"global": sp.csr_matrix(x), "user": user_x},
        ids={"userId": users, "itemId": items})


def _re_model(rng, data):
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "user"),
        intercept_col=2)
    model = RandomEffectModel.zeros_like_dataset(ds, dtype=jnp.float64)
    coefs = [jnp.asarray(rng.normal(0, 1, np.asarray(c).shape))
             for c in model.local_coefs]
    return model.with_coefs(coefs)


def test_device_scorer_matches_numpy(rng):
    data = _dataset(rng)
    fe = FixedEffectModel(
        LogisticRegressionModel(Coefficients(
            jnp.asarray(rng.normal(0, 1, 6)))), "global")
    re = _re_model(rng, data)
    mf = MatrixFactorizationModel(
        "userId", "itemId",
        jnp.asarray(rng.normal(0, 1, (7, 3))),
        jnp.asarray(rng.normal(0, 1, (5, 3))),
        np.unique(data.id_columns["userId"].vocabulary),
        np.unique(data.id_columns["itemId"].vocabulary))
    gm = GameModel({"fixed": fe, "perUser": re, "mf": mf},
                   TaskType.LOGISTIC_REGRESSION)

    scorer = DeviceGameScorer(gm, data, dtype=jnp.float64)
    got = np.asarray(scorer.score(gm))
    want = gm.score(data)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)


def test_device_scorer_unseen_entities_score_zero(rng):
    data = _dataset(rng)
    re = _re_model(rng, data)
    # Fresh dataset with entities the model has never seen.
    data2 = _dataset(np.random.default_rng(99), n=40)
    ids2 = np.asarray(["zz_unknown"] * 40)
    data2 = GameDataset.build(
        responses=data2.responses,
        feature_shards={k: v for k, v in data2.feature_shards.items()},
        ids={"userId": ids2, "itemId": np.asarray(["x"] * 40)})
    gm = GameModel({"perUser": re}, TaskType.LOGISTIC_REGRESSION)
    scorer = DeviceGameScorer(gm, data2, dtype=jnp.float64)
    np.testing.assert_allclose(np.asarray(scorer.score(gm)), 0.0)
    np.testing.assert_allclose(gm.score(data2), 0.0)


def test_device_scorer_updated_params_reuse_structure(rng):
    """Scoring an updated model (same structure) hits the same compiled
    executable and reflects the new parameters."""
    data = _dataset(rng)
    re = _re_model(rng, data)
    gm = GameModel({"perUser": re}, TaskType.LOGISTIC_REGRESSION)
    scorer = DeviceGameScorer(gm, data, dtype=jnp.float64)
    first = np.asarray(scorer.score(gm))

    re2 = re.with_coefs([2.0 * jnp.asarray(c) for c in re.local_coefs])
    gm2 = GameModel({"perUser": re2}, TaskType.LOGISTIC_REGRESSION)
    second = np.asarray(scorer.score(gm2))
    np.testing.assert_allclose(second, 2.0 * first, rtol=1e-10)
    np.testing.assert_allclose(second, gm2.score(data), rtol=1e-10)


def test_device_scorer_factored_random_effect(rng):
    """Factored RE: the learned projection B is a scoring PARAM — an
    updated B must change scores without rebuilding the scorer."""
    from photon_ml_tpu.algorithm.coordinates import (
        FactoredRandomEffectCoordinate,
    )
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
        MFOptimizationConfiguration,
    )

    data = _dataset(rng)
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration(
            "userId", "user", projector_type="IDENTITY"),
        intercept_col=2)
    coord = FactoredRandomEffectCoordinate(
        name="fre", dataset=ds, task_type=TaskType.LOGISTIC_REGRESSION,
        config=GLMOptimizationConfiguration(max_iterations=3),
        latent_config=GLMOptimizationConfiguration(max_iterations=3),
        mf_config=MFOptimizationConfiguration(max_iterations=1,
                                              num_factors=2))
    model = coord.initialize_model()
    model, _ = coord.update_model(model, None, None)
    gm = GameModel({"fre": model}, TaskType.LOGISTIC_REGRESSION)
    scorer = DeviceGameScorer(gm, data, dtype=jnp.float64)
    np.testing.assert_allclose(np.asarray(scorer.score(gm)),
                               gm.score(data), rtol=1e-6, atol=1e-8)
