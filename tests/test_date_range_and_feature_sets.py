"""DateRange resolution (ml/util/DateRange.scala, IOUtils daily-dir
expansion) and NameAndTermFeatureSetContainer parity tests."""

import datetime

import pytest

from photon_ml_tpu.data.index_map import feature_key
from photon_ml_tpu.data.name_and_term import NameAndTermFeatureSetContainer
from photon_ml_tpu.utils.date_range import (
    DateRange,
    resolve_input_dirs,
    resolve_paths_within_date_range,
)


def test_date_range_parse_and_str():
    r = DateRange.from_string("20260101-20260115")
    assert r.start == datetime.date(2026, 1, 1)
    assert r.end == datetime.date(2026, 1, 15)
    assert str(r) == "2026-01-01-2026-01-15"
    assert len(r.days()) == 15


def test_date_range_validation():
    with pytest.raises(ValueError, match="comes after"):
        DateRange.from_string("20260115-20260101")
    with pytest.raises(ValueError, match="parse"):
        DateRange.from_string("garbage")
    with pytest.raises(ValueError, match="parse"):
        DateRange.from_string("20260101-20260115-2026")


def test_date_range_days_ago():
    today = datetime.date(2026, 7, 29)
    r = DateRange.from_days_ago(7, 1, today=today)
    assert r.start == datetime.date(2026, 7, 22)
    assert r.end == datetime.date(2026, 7, 28)
    r2 = DateRange.from_days_ago_string("7-1", today=today)
    assert r2 == r
    with pytest.raises(ValueError, match="negative"):
        DateRange.from_days_ago(-1, 0)


def test_resolve_daily_paths(tmp_path):
    for day in ("2026/01/01", "2026/01/02", "2026/01/04"):
        (tmp_path / "daily" / day).mkdir(parents=True)
    rng = DateRange.from_string("20260101-20260105")
    paths = resolve_paths_within_date_range([tmp_path], rng)
    assert [p.name for p in paths] == ["01", "02", "04"]
    with pytest.raises(FileNotFoundError, match="Missing"):
        resolve_paths_within_date_range([tmp_path], rng,
                                        error_on_missing=True)
    with pytest.raises(FileNotFoundError, match="No data folder"):
        resolve_paths_within_date_range(
            [tmp_path], DateRange.from_string("20270101-20270102"))


def test_resolve_input_dirs_passthrough_and_exclusive(tmp_path):
    assert resolve_input_dirs([tmp_path]) == [tmp_path]
    with pytest.raises(ValueError, match="at most one"):
        resolve_input_dirs([tmp_path], date_range="20260101-20260102",
                           date_range_days_ago="7-1")


def test_name_and_term_container_roundtrip(tmp_path):
    container = NameAndTermFeatureSetContainer({
        "features": {("age", ""), ("height", "cm")},
        "songFeatures": {("tempo", "bpm")},
    })
    imap = container.get_feature_name_and_term_to_index_map(
        ["features", "songFeatures"], add_intercept=True)
    assert len(imap) == 4
    assert imap.get_index(feature_key("tempo", "bpm")) >= 0
    assert imap.intercept_index == 3  # appended last

    container.save_as_text_files(tmp_path)
    loaded = NameAndTermFeatureSetContainer.load_from_text_files(
        tmp_path, ["features", "songFeatures"])
    assert loaded.feature_sets == container.feature_sets


def test_name_and_term_from_avro(tmp_path, rng):
    from tests.test_cli_drivers import _write_glm_avro

    _write_glm_avro(tmp_path / "data", rng, n=30, d=4)
    container = NameAndTermFeatureSetContainer.from_avro(tmp_path / "data")
    assert len(container.feature_sets["features"]) == 4
    imap = container.get_feature_name_and_term_to_index_map(["features"])
    assert len(imap) == 4


def test_game_driver_with_date_partitioned_input(tmp_path, rng):
    from tests.test_cli_drivers import _write_game_avro
    from photon_ml_tpu.cli import game_training_driver

    for day in ("2026/07/01", "2026/07/02"):
        _write_game_avro(tmp_path / "train" / "daily" / day, rng, n=120)
    _write_game_avro(tmp_path / "valid", rng, n=80)
    out = tmp_path / "out"
    summary = game_training_driver.run([
        "--train-input-dirs", str(tmp_path / "train"),
        "--train-date-range", "20260701-20260702",
        "--validate-input-dirs", str(tmp_path / "valid"),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:20,1e-7,1.0,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--num-iterations", "1",
        "--evaluators", "AUC",
    ])
    # Both daily partitions were ingested.
    assert summary["numRows"] == 240


def test_feature_indexing_saves_name_and_term_sets(tmp_path, rng):
    from tests.test_cli_drivers import _write_glm_avro
    from photon_ml_tpu.cli import feature_indexing

    _write_glm_avro(tmp_path / "data", rng, n=20, d=3)
    feature_indexing.run([
        "--data-path", str(tmp_path / "data"),
        "--output-dir", str(tmp_path / "out"),
        "--save-name-and-term-sets", "true",
    ])
    sets_file = tmp_path / "out" / "name-and-term-sets" / "features.txt"
    assert sets_file.exists()
    assert len(sets_file.read_text().splitlines()) == 3
