"""Factor-table cache tests (data/factor_cache.py): ALX-style pow-2
observation-count bucketing, replay-aware factor-shard eviction, and the
f32/bf16/redecode spill tiers re-pointed at MUTABLE factor tables."""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.data.factor_cache import (
    DeviceFactorCache,
    FactorSpill,
    encode_factor_spill,
    plan_factors,
    restore_spilled_factors,
)


# ---------------------------------------------------------------------------
# Planning
# ---------------------------------------------------------------------------


def test_plan_obs_count_pow2_bucketing():
    """Entities land in next_pow2(count) density classes — including the
    exact-boundary counts (4 -> class 4, 5 -> class 8)."""
    vocab = np.asarray([f"e{i}" for i in range(8)])
    counts = np.asarray([1, 2, 3, 4, 5, 8, 9, 16])
    plan = plan_factors(vocab, counts, entities_per_shard=64)
    cls_of = {}
    for s in plan.shards:
        for c in s.codes:
            cls_of[int(c)] = s.obs_bucket
    assert [cls_of[i] for i in range(8)] == [1, 2, 4, 4, 8, 8, 16, 16]
    # deterministic: same inputs -> same shard list
    plan2 = plan_factors(vocab, counts, entities_per_shard=64)
    assert [tuple(s.codes) for s in plan2.shards] == \
        [tuple(s.codes) for s in plan.shards]


def test_plan_shard_boundary_splits_and_epad():
    """Entity counts straddling the entities_per_shard boundary split
    into multiple pow-2-padded shards; e_pad respects the minimum."""
    vocab = np.asarray([f"e{i:02d}" for i in range(9)])
    counts = np.full(9, 4)  # one class
    plan = plan_factors(vocab, counts, entities_per_shard=4,
                        min_entities_pad=8)
    sizes = [s.n_entities for s in plan.shards]
    assert sizes == [4, 4, 1]
    assert [s.e_pad for s in plan.shards] == [8, 8, 8]
    # exactly at the boundary: no ghost shard
    plan8 = plan_factors(vocab[:8], counts[:8], entities_per_shard=4)
    assert [s.n_entities for s in plan8.shards] == [4, 4]
    # pow-2 pad grows past the minimum
    plan_big = plan_factors(
        np.asarray([f"x{i:03d}" for i in range(21)]), np.full(21, 2),
        entities_per_shard=64, min_entities_pad=8)
    assert [s.e_pad for s in plan_big.shards] == [32]


def test_plan_roundtrip_and_zero_count():
    vocab = np.asarray(["a", "b", "c", "zero"])
    counts = np.asarray([3, 1, 7, 0])
    plan = plan_factors(vocab, counts, entities_per_shard=2)
    # every code maps to a (shard, slot) that maps back
    for code in range(4):
        s = plan.shards[plan.shard_of_code[code]]
        assert s.codes[plan.slot_of_code[code]] == code
    # zero-observation entities ride the smallest class (solvable to 0)
    zero_code = int(np.flatnonzero(vocab == "zero")[0])
    assert plan.shards[plan.shard_of_code[zero_code]].obs_bucket == 1
    # name join: unknown -> -1
    assert list(plan.codes_of(np.asarray(["c", "nope"]))) == [2, -1]
    assert sum(plan.obs_bucket_histogram().values()) == 4


def test_plan_validation():
    with pytest.raises(ValueError, match="entities_per_shard"):
        plan_factors(np.asarray(["a"]), np.asarray([1]),
                     entities_per_shard=0)
    with pytest.raises(ValueError, match="counts"):
        plan_factors(np.asarray(["a", "b"]), np.asarray([1]))


# ---------------------------------------------------------------------------
# Spill codec
# ---------------------------------------------------------------------------


def test_factor_spill_f32_roundtrip_bitwise(rng):
    g = rng.normal(0, 1, (8, 3)).astype(np.float32)
    spill = encode_factor_spill(g, "f32")
    assert spill.dtype_tag == "f32" and spill.nbytes == g.nbytes
    out = np.asarray(restore_spilled_factors(spill))
    assert out.tobytes() == g.tobytes()


def test_factor_spill_bf16_half_bytes_and_lossless_on_quantized(rng):
    import ml_dtypes

    g = rng.normal(0, 1, (16, 4)).astype(np.float32)
    # the cache quantizes at write; a quantized table round-trips exactly
    gq = g.astype(ml_dtypes.bfloat16).astype(np.float32)
    spill = encode_factor_spill(gq, "bf16")
    assert spill.nbytes == gq.nbytes // 2
    out = np.asarray(restore_spilled_factors(spill))
    assert out.tobytes() == gq.tobytes()
    # and the quantization error is the documented bf16 bound
    assert np.max(np.abs(gq - g)) <= 2.0 ** -8 * np.max(np.abs(g))


def test_factor_spill_validation():
    with pytest.raises(ValueError, match="spill_dtype"):
        encode_factor_spill(np.zeros((2, 2), np.float32), "f16")


# ---------------------------------------------------------------------------
# Cache residency
# ---------------------------------------------------------------------------


def _plan(n_shards=4, e_pad=8):
    vocab = np.asarray([f"e{i:02d}" for i in range(n_shards * 4)])
    counts = np.full(len(vocab), 2)
    plan = plan_factors(vocab, counts, entities_per_shard=4,
                        min_entities_pad=e_pad)
    assert plan.n_shards == n_shards
    return plan


def _fill(cache, k=2, seed=0):
    rng = np.random.default_rng(seed)
    raw = []
    for s in cache.plan.shards:
        g = rng.normal(0, 1, (s.e_pad, k)).astype(np.float32)
        raw.append(np.asarray(cache.write(s.index, g)))
    return raw


def test_cache_resident_write_read_and_stats():
    cache = DeviceFactorCache(_plan(), num_factors=2)
    raw = _fill(cache)
    for i, g in enumerate(raw):
        assert np.asarray(cache.ensure(i)).tobytes() == g.tobytes()
    st = cache.stats()
    assert st["hits"] == 4 and st["misses"] == 0 and st["evictions"] == 0
    assert st["resident_shards"] == 4 and st["spill_bytes_host"] == 0
    assert st["device_bytes"] == 4 * (4 * 8 * 2)
    assert set(st) >= {"hits", "misses", "evictions", "bytes_reuploaded",
                       "spill_bytes_written", "redecodes", "shards",
                       "entities", "num_factors", "e_pad_buckets",
                       "obs_bucket_histogram", "hbm_budget_bytes",
                       "device_bytes", "peak_device_bytes", "spill_dtype",
                       "spill_source", "spill_bytes_host",
                       "resident_shards"}


def test_cache_read_before_write_raises():
    cache = DeviceFactorCache(_plan(), num_factors=2)
    with pytest.raises(RuntimeError, match="never written"):
        cache.ensure(0)


def test_cache_replay_aware_eviction_and_f32_bitwise_restore():
    """Budget for 2 of 4 shards: the write sequence 0..3 keeps a
    sensible resident set under the furthest-next-use rule, misses
    restore the EXACT evicted bytes, and the in-hand shard is never
    evicted."""
    shard_bytes = 4 * 8 * 2
    cache = DeviceFactorCache(_plan(), num_factors=2,
                              hbm_budget_bytes=2 * shard_bytes)
    raw = _fill(cache)
    st = cache.stats()
    assert st["evictions"] >= 2
    assert st["resident_shards"] == 2
    assert st["spill_bytes_host"] > 0
    # a full fixed-order read epoch restores everything bitwise
    for i, g in enumerate(raw):
        assert np.asarray(cache.ensure(i)).tobytes() == g.tobytes()
    st = cache.stats()
    assert st["misses"] >= 2 and st["bytes_reuploaded"] > 0
    assert cache.device_bytes <= 2 * shard_bytes
    # one-shard budget: the pinned write always survives
    tiny = DeviceFactorCache(_plan(), num_factors=2, hbm_budget_bytes=1)
    raws = _fill(tiny)
    assert tiny.stats()["resident_shards"] == 1
    assert np.asarray(tiny.ensure(3)).tobytes() == raws[3].tobytes()


def test_cache_rewrite_drops_stale_spill():
    """Factors mutate per sweep: a re-write supersedes the old spill
    record and the next miss restores the NEW bytes."""
    shard_bytes = 4 * 8 * 2
    cache = DeviceFactorCache(_plan(), num_factors=2,
                              hbm_budget_bytes=2 * shard_bytes)
    _fill(cache, seed=0)
    raw2 = _fill(cache, seed=1)  # second sweep's writes
    for i, g in enumerate(raw2):
        assert np.asarray(cache.ensure(i)).tobytes() == g.tobytes()


def test_cache_bf16_quantizes_at_write_residency_independent(rng):
    """bf16 is applied AT WRITE, evicted or not: the returned canonical
    table equals the bf16 round trip, restores are bitwise the resident
    copy, and spill records are half the f32 bytes."""
    import ml_dtypes

    g = rng.normal(0, 1, (8, 2)).astype(np.float32)
    gq = g.astype(ml_dtypes.bfloat16).astype(np.float32)
    shard_bytes = 4 * 8 * 2
    resident = DeviceFactorCache(_plan(), num_factors=2,
                                 spill_dtype="bf16",
                                 hbm_budget_bytes=10 ** 9)
    evicting = DeviceFactorCache(_plan(), num_factors=2,
                                 spill_dtype="bf16",
                                 hbm_budget_bytes=shard_bytes)
    for cache in (resident, evicting):
        out = np.asarray(cache.write(0, g))
        assert out.tobytes() == gq.tobytes()
        for s in cache.plan.shards[1:]:
            cache.write(s.index, g)
    assert evicting.stats()["evictions"] > 0
    assert resident.stats()["evictions"] == 0
    for i in range(4):
        a = np.asarray(resident.ensure(i))
        b = np.asarray(evicting.ensure(i))
        assert a.tobytes() == b.tobytes() == gq.tobytes()
    assert evicting.stats()["spill_bytes_written"] > 0
    # bf16 spill records are half of the f32 table bytes
    spilled = [e for e in evicting.entries if e.spill is not None]
    for e in spilled:
        assert e.spill.nbytes == e.factor_bytes // 2


def test_cache_redecode_tier_rederives_and_keeps_no_host_bytes(rng):
    g0 = rng.normal(0, 1, (8, 2)).astype(np.float32)
    calls = []

    def rederive(index):
        calls.append(index)
        return jnp.asarray(g0 + np.float32(index))

    shard_bytes = 4 * 8 * 2
    cache = DeviceFactorCache(_plan(), num_factors=2,
                              spill_source="redecode",
                              hbm_budget_bytes=shard_bytes,
                              redecode=rederive)
    for s in cache.plan.shards:
        cache.write(s.index, g0 + np.float32(s.index))
    assert cache.stats()["evictions"] == 3
    assert cache.stats()["spill_bytes_host"] == 0
    for i in range(4):
        out = np.asarray(cache.ensure(i))
        assert out.tobytes() == (g0 + np.float32(i)).tobytes()
    # capacity-1 residency: every read in the epoch is a re-derivation
    assert cache.stats()["redecodes"] == len(calls) == 4
    assert cache.stats()["spill_bytes_host"] == 0


def test_cache_redecode_without_hook_raises():
    cache = DeviceFactorCache(_plan(), num_factors=2,
                              spill_source="redecode",
                              hbm_budget_bytes=1)
    _fill(cache)
    with pytest.raises(RuntimeError, match="no spill record"):
        cache.ensure(0)


def test_cache_validation():
    plan = _plan()
    with pytest.raises(ValueError, match="pick one"):
        DeviceFactorCache(plan, 2, spill_dtype="bf16",
                          spill_source="redecode")
    with pytest.raises(ValueError, match="spill_dtype"):
        DeviceFactorCache(plan, 2, spill_dtype="f64")
    with pytest.raises(ValueError, match="spill_source"):
        DeviceFactorCache(plan, 2, spill_source="disk")
    with pytest.raises(ValueError, match="num_factors"):
        DeviceFactorCache(plan, 0)
    cache = DeviceFactorCache(plan, 2)
    with pytest.raises(ValueError, match="shape"):
        cache.write(0, np.zeros((4, 2), np.float32))


def test_restore_spilled_factors_is_the_blessed_path(rng):
    """Direct FactorSpill construction + restore agree with the
    encode path (the codec's two halves cannot diverge)."""
    g = rng.normal(0, 1, (8, 2)).astype(np.float32)
    direct = FactorSpill(enc=g.copy(), dtype_tag="f32")
    assert np.asarray(restore_spilled_factors(direct)).tobytes() == \
        np.asarray(restore_spilled_factors(
            encode_factor_spill(g, "f32"))).tobytes()
