"""Test harness: emulate an 8-device TPU mesh on CPU.

The analog of the reference's Spark `local[4]` integration harness
(photon-test-utils/.../SparkTestUtils.scala:191): the same sharding /
collective code paths run on 8 virtual CPU devices, so multi-chip logic is
exercised without TPU hardware. Must run before jax initializes — hence the
env mutation at import time of this conftest.

f64 is enabled so golden-value tests can run at Breeze-like precision; device
code paths stay dtype-polymorphic and run f32/bf16 on real TPU.
"""

import os

# Force CPU for tests even when the session exposes a TPU (JAX_PLATFORMS=axon):
# unit/integration tiers need f64 and 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Second CI configuration (SURVEY hard-part 3): PHOTON_ML_TPU_TEST_F32=1
# runs the suite WITHOUT x64 — every array stays f32, the dtype the real
# TPU executes. tests/test_f32_parity.py asserts f32-vs-f64 agreement of
# optimizer outcomes regardless of mode.
_F32_MODE = os.environ.get("PHOTON_ML_TPU_TEST_F32") == "1"
if not _F32_MODE:
    os.environ.setdefault("JAX_ENABLE_X64", "1")

# Plugins (flax/chex) may have imported jax before this conftest ran, in which
# case the env vars above were read too late — re-apply through jax.config
# (safe while the backend is uninitialized).
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", not _F32_MODE)

import numpy as np
import pytest

assert jax.device_count() == 8, (
    f"test harness expected 8 virtual CPU devices, got {jax.device_count()}"
)

F32_MODE = _F32_MODE

# dtype-aware golden tolerances: f32 carries ~7 significant digits, so
# equality/closed-form assertions that demand 1e-12 in the f64 config get
# a calibrated bound in the f32 config instead of a false failure.
GOLD_RTOL = 1e-5 if F32_MODE else 1e-12
SOLVE_RTOL = 2e-3 if F32_MODE else 1e-5  # optimizer-vs-optimum agreement


def gold(rtol: float, f32_floor: float = None) -> float:
    """A test's f64-calibrated tolerance, floored at the f32 bound when the
    suite runs in the PHOTON_ML_TPU_TEST_F32=1 config."""
    if not F32_MODE:
        return rtol
    return max(rtol, f32_floor if f32_floor is not None else GOLD_RTOL)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "needs_f64: test depends on double precision (finite differences, "
        "sub-1e-8 golden values) and is skipped in the f32 CI config")
    config.addinivalue_line(
        "markers",
        "native_decoder: test exercises the native C Avro decoder "
        "(photon_ml_tpu/native/_avro_native.c) and is skipped cleanly "
        "when the extension is unbuilt (no C compiler) or disabled via "
        "PHOTON_ML_TPU_NO_NATIVE=1")
    config.addinivalue_line(
        "markers",
        "slow: heavyweight test — forced-device subprocess suites "
        "(full jax-init training-driver children) and the longest "
        "solver-parity sweeps whose cheaper siblings keep the "
        "coverage; excluded from the tier-1 `-m 'not slow'` budget "
        "run, still runs in full CI (ROADMAP.md §verify)")


def _native_decoder_available() -> bool:
    from photon_ml_tpu.native import load_avro_native

    native = load_avro_native()
    return native is not None and hasattr(native, "decode_training_block")


def pytest_collection_modifyitems(config, items):
    if any("native_decoder" in item.keywords for item in items) \
            and not _native_decoder_available():
        skip_native = pytest.mark.skip(
            reason="native C avro decoder unavailable (extension unbuilt "
                   "or PHOTON_ML_TPU_NO_NATIVE=1)")
        for item in items:
            if "native_decoder" in item.keywords:
                item.add_marker(skip_native)
    if not F32_MODE:
        return
    skip = pytest.mark.skip(
        reason="requires f64 (PHOTON_ML_TPU_TEST_F32=1 config)")
    for item in items:
        if "needs_f64" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture
def multi_device():
    """Run a python snippet under a jax that sees EXACTLY ``n_devices``
    virtual CPU devices, in a fresh subprocess (``XLA_FLAGS
    --xla_force_host_platform_device_count`` must land before jax
    initializes — the tests/multihost_worker.py pattern). This harness
    process is pinned to 8 virtual devices, so total-device-count
    behavior (``--mesh-devices`` on an N-chip host) is only testable in
    a child; the fixture SKIPS (never fails) when a child cannot be
    spawned at all — constrained sandboxes — and raises with the
    child's output on a genuine in-child failure.

    Usage::

        def test_x(multi_device):
            proc = multi_device(2, "import jax; print(jax.device_count())")
            assert proc.stdout.strip() == "2"
    """
    import subprocess
    import sys

    from photon_ml_tpu.utils.virtual_devices import forced_cpu_device_env

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(n_devices: int, code: str, timeout: float = 600.0,
            env: dict = None) -> "subprocess.CompletedProcess":
        child_env = forced_cpu_device_env(n_devices, os.environ)
        child_env.update(env or {})
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], env=child_env,
                capture_output=True, text=True, timeout=timeout,
                cwd=repo_root)
        except subprocess.TimeoutExpired as exc:
            raise AssertionError(
                f"{n_devices}-device subprocess hung past {timeout}s:\n"
                f"STDOUT:\n{exc.stdout}\nSTDERR:\n{exc.stderr}") from exc
        except (OSError, subprocess.SubprocessError) as exc:
            pytest.skip(
                f"cannot spawn a {n_devices}-device subprocess: {exc!r}")
        if proc.returncode != 0:
            raise AssertionError(
                f"{n_devices}-device subprocess failed "
                f"(rc={proc.returncode}):\nSTDOUT:\n{proc.stdout}\n"
                f"STDERR:\n{proc.stderr}")
        return proc

    return run


@pytest.fixture
def tracing_guard():
    """Shared retrace-guard fixture (utils/tracing_guard.py): yields a
    fresh TracingGuard; budgets a test declares (track(..., max_traces=)
    or set_budget(total)) are verified at teardown, so a compile-count
    regression fails the test even without an explicit assert."""
    from photon_ml_tpu.utils.tracing_guard import TracingGuard

    guard = TracingGuard()
    yield guard
    guard.verify()
