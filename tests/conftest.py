"""Test harness: emulate an 8-device TPU mesh on CPU.

The analog of the reference's Spark `local[4]` integration harness
(photon-test-utils/.../SparkTestUtils.scala:191): the same sharding /
collective code paths run on 8 virtual CPU devices, so multi-chip logic is
exercised without TPU hardware. Must run before jax initializes — hence the
env mutation at import time of this conftest.

f64 is enabled so golden-value tests can run at Breeze-like precision; device
code paths stay dtype-polymorphic and run f32/bf16 on real TPU.
"""

import os

# Force CPU for tests even when the session exposes a TPU (JAX_PLATFORMS=axon):
# unit/integration tiers need f64 and 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# Plugins (flax/chex) may have imported jax before this conftest ran, in which
# case the env vars above were read too late — re-apply through jax.config
# (safe while the backend is uninitialized).
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest

assert jax.device_count() == 8, (
    f"test harness expected 8 virtual CPU devices, got {jax.device_count()}"
)


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)
