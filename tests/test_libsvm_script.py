"""dev_scripts/libsvm_text_to_trainingexample_avro.py: round-trip a small
LibSVM text file into TrainingExampleAvro and decode it back through BOTH
container readers — the pure-python datum decoder and the native C block
decoder — plus the training ingest fast path."""

import importlib.util
from pathlib import Path

import numpy as np
import pytest

from photon_ml_tpu.io.avro_codec import read_container

_SCRIPT = (Path(__file__).resolve().parents[1] / "dev_scripts"
           / "libsvm_text_to_trainingexample_avro.py")


def _load_script():
    spec = importlib.util.spec_from_file_location("libsvm_script", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def script():
    return _load_script()


LIBSVM_TEXT = """\
+1 1:0.5 3:-1.25 7:2.0  # trailing comment
-1 2:1.0 7:0.125
# full-line comment

+1 1:-3.5
-1 5:4.0 6:-0.75
"""


@pytest.fixture
def converted(tmp_path, script):
    src = tmp_path / "data.libsvm"
    src.write_text(LIBSVM_TEXT)
    out = tmp_path / "avro-out"
    n = script.convert(src, out, regression=False, zero_based=False)
    assert n == 4
    return out / "part-00000.avro"


def _expected_rows():
    # 1-based input indices -> 0-based names; -1/+1 -> 0/1 labels.
    return [
        (1.0, {"0": 0.5, "2": -1.25, "6": 2.0}),
        (0.0, {"1": 1.0, "6": 0.125}),
        (1.0, {"0": -3.5}),
        (0.0, {"4": 4.0, "5": -0.75}),
    ]


def _decode(path):
    recs = list(read_container(path))
    return [(r["label"],
             {f["name"]: f["value"] for f in r["features"]},
             r["uid"], r["weight"], r["offset"], r["metadataMap"])
            for r in recs]


def test_python_reader_roundtrip(converted, monkeypatch):
    import photon_ml_tpu.native as nat

    monkeypatch.setattr(nat, "_loaded", True)
    monkeypatch.setattr(nat, "_module", None)
    rows = _decode(converted)
    for (label, feats), (got_label, got_feats, uid, w, off, meta) in zip(
            _expected_rows(), rows):
        assert got_label == label
        assert got_feats == feats
        assert uid is not None  # line numbers become uids
        assert w is None and off is None and meta is None


@pytest.mark.native_decoder
def test_c_reader_matches_python_reader(converted, monkeypatch):
    import photon_ml_tpu.native as nat

    native_rows = _decode(converted)  # C decode_block path
    saved = (nat._loaded, nat._module)
    try:
        nat._loaded, nat._module = True, None
        python_rows = _decode(converted)
    finally:
        nat._loaded, nat._module = saved
    assert native_rows == python_rows
    assert [r[0] for r in native_rows] == [1.0, 0.0, 1.0, 0.0]


def test_training_ingest_reads_converted_file(converted):
    from photon_ml_tpu.data.avro_reader import read_labeled_points

    mat, labels, offsets, weights, uids, imap = read_labeled_points(
        converted, add_intercept=False, ingest_workers=1)
    np.testing.assert_array_equal(labels, [1.0, 0.0, 1.0, 0.0])
    np.testing.assert_array_equal(offsets, np.zeros(4))
    np.testing.assert_array_equal(weights, np.ones(4))
    assert uids == ["1", "2", "5", "6"]  # source line numbers
    dense = {}
    for i in range(4):
        row = mat[i]
        for j, v in zip(row.indices, row.data):
            dense[(i, imap.get_feature_name(j))] = v
    assert dense[(0, "0\x01")] == 0.5
    assert dense[(3, "5\x01")] == -0.75
    assert mat.nnz == 8


def test_regression_and_zero_based_flags(tmp_path, script):
    src = tmp_path / "reg.libsvm"
    src.write_text("2.5 0:1.0 3:2.0\n-4.25 1:0.5\n")
    out = tmp_path / "reg-out"
    n = script.convert(src, out, regression=True, zero_based=True)
    assert n == 2
    rows = _decode(out / "part-00000.avro")
    assert [r[0] for r in rows] == [2.5, -4.25]  # raw labels kept
    assert rows[0][1] == {"0": 1.0, "3": 2.0}  # indices used as-is


def test_malformed_line_is_a_clean_error(tmp_path, script):
    src = tmp_path / "bad.libsvm"
    src.write_text("+1 1:0.5\n-1 notafeature\n")
    out = tmp_path / "bad-out"
    with pytest.raises(SystemExit, match="bad.libsvm:2"):
        script.convert(src, out, regression=False, zero_based=False)


def test_main_entrypoint(tmp_path, script, capsys):
    src = tmp_path / "m.libsvm"
    src.write_text("+1 1:1.0\n")
    out = tmp_path / "m-out"
    script.main([str(src), str(out)])
    assert "wrote 1 records" in capsys.readouterr().out
    assert (out / "part-00000.avro").exists()
