"""Telemetry layer: registry metrics (histogram percentile math incl.
exact and bucket-boundary cases), span nesting / thread isolation,
Chrome trace export, and the disabled-mode zero-allocation fast path."""

import gc
import json
import sys
import threading
import time

import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import Histogram, span
from photon_ml_tpu.telemetry.spans import _NOOP


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Telemetry is process-global: every test starts reset+disabled and
    leaves it that way."""
    telemetry.disable()
    telemetry.reset()
    telemetry.tracer().record_events = False
    yield
    telemetry.disable()
    telemetry.reset()
    telemetry.tracer().record_events = False


def _on():
    telemetry.enable()


# ---------------------------------------------------------------------------
# Histogram percentile math
# ---------------------------------------------------------------------------


def test_histogram_empty_returns_none():
    h = Histogram("t.empty")
    assert h.quantile(0.5) is None
    snap = h.snapshot()
    assert snap["count"] == 0
    assert snap["p50"] is None and snap["p99"] is None
    assert snap["mean"] is None


def test_histogram_single_sample_exact_for_every_quantile():
    _on()
    h = Histogram("t.single", buckets=[1.0, 10.0, 100.0])
    h.observe(3.7)
    # min==max clamp makes a single sample exact regardless of how wide
    # its bucket is.
    for q in (0.0, 0.25, 0.5, 0.95, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(3.7)


def test_histogram_all_equal_samples_exact():
    _on()
    h = Histogram("t.equal", buckets=[1.0, 2.0, 4.0])
    for _ in range(17):
        h.observe(2.0)
    assert h.quantile(0.5) == pytest.approx(2.0)
    assert h.quantile(0.99) == pytest.approx(2.0)


def test_histogram_bucket_boundary_le_semantics():
    # A sample equal to a boundary lands in the bucket that boundary
    # CLOSES (Prometheus `le`), not the one it opens.
    _on()
    h = Histogram("t.bound", buckets=[1.0, 2.0, 4.0])
    h.observe(1.0)
    h.observe(2.0)
    h.observe(4.0)
    h.observe(5.0)  # overflow
    counts = h.bucket_counts()
    assert counts[1.0] == 1
    assert counts[2.0] == 1
    assert counts[4.0] == 1
    assert counts["+inf"] == 1


def test_histogram_interpolation_within_bucket():
    # Documented math: rank q*count falls in a bucket; linear
    # interpolation between the bucket edges, clamped to [min, max].
    _on()
    h = Histogram("t.interp", buckets=[10.0])
    for v in (2.0, 4.0, 6.0, 8.0):
        h.observe(v)
    # p50: target rank 2 of 4 in bucket (min..10] -> lo=min=2, frac=0.5
    # -> 2 + 0.5*(10-2) = 6 ... wait: lo is min for the first bucket.
    assert h.quantile(0.5) == pytest.approx(6.0)
    assert h.quantile(0.0) == pytest.approx(2.0)  # clamps to min
    assert h.quantile(1.0) == pytest.approx(8.0)  # clamps to max


def test_histogram_percentiles_bounded_by_bucket_width():
    _on()
    h = Histogram("t.width")  # default latency buckets, ~17% relative
    import numpy as np

    rng = np.random.default_rng(3)
    samples = rng.uniform(1e-4, 1e-1, size=500)
    for v in samples:
        h.observe(float(v))
    for q in (0.5, 0.95, 0.99):
        exact = float(np.quantile(samples, q))
        est = h.quantile(q)
        assert est == pytest.approx(exact, rel=0.25)
    assert h.count == 500
    assert h.sum == pytest.approx(float(samples.sum()))
    # Percentile ordering survives bucketization.
    assert h.quantile(0.5) <= h.quantile(0.95) <= h.quantile(0.99)


def test_histogram_batched_observe_matches_individual():
    """observe(v, n=k) and observe_many(values) are locking/allocation
    optimizations for the serving hot path — the resulting histogram
    state must be IDENTICAL to the equivalent individual observes
    (docs/OBSERVABILITY.md §Histogram semantics)."""
    _on()
    values = [0.3, 1.0, 1.0, 2.5, 7.0, 7.0, 7.0, 40.0, 0.05]
    ref = Histogram("t.batch.ref", buckets=[1.0, 10.0, 100.0])
    for v in values:
        ref.observe(v)

    many = Histogram("t.batch.many", buckets=[1.0, 10.0, 100.0])
    many.observe_many(values)
    assert many._counts == ref._counts
    assert many.count == ref.count
    assert many.snapshot() == ref.snapshot()

    n_style = Histogram("t.batch.n", buckets=[1.0, 10.0, 100.0])
    n_style.observe(0.3)
    n_style.observe(1.0, n=2)   # boundary value: le semantics w/ n
    n_style.observe(2.5)
    n_style.observe(7.0, n=3)
    n_style.observe(40.0)
    n_style.observe(0.05)
    assert n_style._counts == ref._counts
    assert n_style.snapshot() == ref.snapshot()

    # empty batch is a no-op, and disabled batches stay no-ops
    many.observe_many([])
    assert many.count == ref.count
    telemetry.disable()
    many.observe_many([1.0, 2.0])
    many.observe(1.0, n=5)
    assert many.count == ref.count


def test_histogram_quantile_validates_range():
    h = Histogram("t.range")
    with pytest.raises(ValueError):
        h.quantile(1.5)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_get_or_create_and_snapshot_schema():
    _on()
    c = telemetry.counter("t.counter")
    assert telemetry.counter("t.counter") is c
    c.inc()
    c.inc(5)
    telemetry.gauge("t.gauge").set(3.5)
    telemetry.histogram("t.hist").observe(0.01)
    snap = telemetry.snapshot()
    assert snap["counters"]["t.counter"] == 6
    assert snap["gauges"]["t.gauge"] == 3.5
    h = snap["histograms"]["t.hist"]
    assert set(h) == {"count", "sum", "mean", "min", "max",
                      "p50", "p95", "p99"}
    assert h["count"] == 1
    # Every metric name in the snapshot is snake_case (dots separate
    # namespaces) — the schema contract of docs/OBSERVABILITY.md.
    for group in snap.values():
        for name in group:
            assert name == name.lower() and " " not in name


def test_registry_mutation_calls_counts_calls_not_values():
    _on()
    c = telemetry.counter("t.calls")
    c.inc(1000)  # one call, value 1000
    telemetry.histogram("t.calls_h").observe(1.0)
    assert telemetry.registry().mutation_calls() == 2


def test_registry_reset_zeroes_but_keeps_handles():
    _on()
    c = telemetry.counter("t.reset")
    c.inc()
    telemetry.reset()
    assert c.value == 0
    assert telemetry.counter("t.reset") is c


# ---------------------------------------------------------------------------
# Disabled fast path
# ---------------------------------------------------------------------------


def test_disabled_mutations_are_noops():
    c = telemetry.counter("t.off")
    h = telemetry.histogram("t.offh")
    g = telemetry.gauge("t.offg")
    c.inc()
    h.observe(1.0)
    g.set(2.0)
    assert c.value == 0 and h.count == 0 and g.value == 0.0


def test_disabled_span_is_shared_noop_singleton():
    # Structural zero-allocation proof: span() returns ONE shared object.
    assert span("a") is _NOOP
    assert span("b") is _NOOP
    assert telemetry.timed_span("c") is _NOOP


def test_disabled_fast_path_zero_allocation_and_cheap():
    c = telemetry.counter("t.zero")
    h = telemetry.histogram("t.zeroh")

    def loop(n):
        for _ in range(n):
            with span("x"):
                pass
            c.inc()
            h.observe(1.0)

    loop(2000)  # warm up allocators / method caches
    gc.disable()
    try:
        before = sys.getallocatedblocks()
        loop(2000)
        after = sys.getallocatedblocks()
    finally:
        gc.enable()
    assert after - before <= 8  # loop bookkeeping only, nothing per-op

    n = 20_000
    t0 = time.perf_counter()
    loop(n)
    per_op_us = (time.perf_counter() - t0) / n * 1e6
    # One span + inc + observe, all disabled: single-digit microseconds
    # even on a loaded 1-core host (measured ~0.5 us).
    assert per_op_us < 25.0


# ---------------------------------------------------------------------------
# Spans: nesting, threads, attribution, export
# ---------------------------------------------------------------------------


def test_span_nesting_self_time():
    _on()
    with span("outer"):
        time.sleep(0.01)
        with span("inner"):
            time.sleep(0.03)
    att = telemetry.stage_attribution()
    assert att["outer"]["count"] == 1 and att["inner"]["count"] == 1
    assert att["inner"]["total_s"] >= 0.03
    assert att["outer"]["total_s"] >= 0.04
    # Self time excludes the nested span.
    assert att["outer"]["self_s"] == pytest.approx(
        att["outer"]["total_s"] - att["inner"]["total_s"], abs=5e-3)


def test_span_thread_isolation():
    _on()

    def worker():
        with span("worker_stage"):
            time.sleep(0.03)

    with span("main_stage"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    att = telemetry.stage_attribution()
    # The worker span ran INSIDE main_stage's wall window but is not its
    # child: main_stage keeps its full self time.
    assert att["main_stage"]["self_s"] == pytest.approx(
        att["main_stage"]["total_s"], abs=5e-3)
    assert att["worker_stage"]["total_s"] >= 0.03
    # Main-thread coverage counts only the driver thread's spans.
    covered = telemetry.tracer().main_thread_covered_seconds()
    assert covered == pytest.approx(att["main_stage"]["self_s"], abs=5e-3)


def test_chrome_trace_export_is_perfetto_loadable_json(tmp_path):
    telemetry.enable(trace=True)

    def worker():
        with span("decode"):
            time.sleep(0.005)

    with span("score"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    out = tmp_path / "trace.json"
    telemetry.export_chrome_trace(out)
    doc = json.loads(out.read_text())
    assert "traceEvents" in doc
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    assert {e["name"] for e in xs} == {"score", "decode"}
    for e in xs:
        assert set(e) >= {"name", "ph", "pid", "tid", "ts", "dur"}
        assert e["dur"] > 0
    # Two threads -> two tracks, the main one named "driver".
    assert len({e["tid"] for e in xs}) == 2
    assert any(e["args"]["name"] == "driver" for e in metas)


def test_trace_events_not_recorded_without_trace_flag():
    telemetry.enable(trace=False)
    with span("quiet"):
        pass
    assert telemetry.tracer().events == []
    # ... but aggregation still happened.
    assert "quiet" in telemetry.stage_attribution()


def test_timed_span_observes_histogram_and_counter():
    _on()
    h = telemetry.histogram("t.iter")
    c = telemetry.counter("t.iters")
    with telemetry.timed_span("step", histogram=h, counter=c):
        time.sleep(0.005)
    assert h.count == 1
    assert h.quantile(0.5) >= 0.005
    assert c.value == 1
    assert "step" in telemetry.stage_attribution()


def test_attribution_summary_fraction():
    _on()
    t0 = time.perf_counter()
    with span("phase_a"):
        time.sleep(0.02)
    with span("phase_b"):
        time.sleep(0.02)
    wall = time.perf_counter() - t0
    s = telemetry.attribution_summary(wall)
    assert s["metrics"]["counters"] == {} or isinstance(
        s["metrics"]["counters"], dict)
    assert s["attributed_wall_frac"] > 0.9
    assert s["attributed_wall_seconds"] <= s["wall_seconds"] * 1.01


# ---------------------------------------------------------------------------
# Adoption: spans flow out of the real pipeline pieces
# ---------------------------------------------------------------------------


def test_prefetcher_and_window_report_wait_stages():
    import jax.numpy as jnp

    from photon_ml_tpu.data.device_feed import (
        HostPrefetcher,
        InFlightWindow,
    )

    _on()
    items = list(range(5))
    out = list(HostPrefetcher(iter(items), depth=2))
    assert out == items
    win = InFlightWindow(depth=1)
    done = []
    for i in range(3):
        d = win.push(jnp.asarray([i]))
        if d is not None:
            done.append(d)
    done.extend(win.drain())
    att = telemetry.stage_attribution()
    assert att["prefetch_wait"]["count"] >= 5
    assert att["device_wait"]["count"] >= 3


def test_block_stream_decode_seconds_accumulates(tmp_path):
    from photon_ml_tpu.data.block_stream import BlockGameStream
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    recs = [{"uid": str(i), "label": float(i % 2), "offset": 0.0,
             "weight": 1.0,
             "features": [{"name": "f0", "term": "", "value": 1.0}],
             "metadataMap": None}
            for i in range(10)]
    path = tmp_path / "in.avro"
    write_container(path, schemas.TRAINING_EXAMPLE, recs)
    maps = {"global": IndexMap({feature_key("f0"): 0})}
    stream = BlockGameStream(str(path), id_types=[],
                             feature_shard_maps=maps, batch_rows=4,
                             feeder="python", prefetch_depth=0)
    assert sum(ds.num_rows for ds in stream) == 10
    st = stream.stats()
    assert st["decode_seconds"] > 0.0
    assert st["batches"] == 3
