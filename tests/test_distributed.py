"""Distributed tests on the 8-virtual-device CPU mesh — the analog of the
reference's Spark local[4] integration harness (SparkTestUtils.scala:191):
the same sharding/collective code paths, no TPU pod needed.
"""

import numpy as np

from tests.conftest import gold
import jax
import jax.numpy as jnp
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.ops import DenseFeatures, GLMObjective, LogisticLoss
from photon_ml_tpu.ops.features import csr_from_scipy
from photon_ml_tpu.ops.glm_objective import make_batch
from photon_ml_tpu.optimization import minimize_lbfgs, minimize_tron
from photon_ml_tpu.parallel import make_mesh, replicate, shard_batch, shard_block


def _logistic(rng, n=96, d=6):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    y = (rng.random(n) < 0.5).astype(np.float64)
    return x, y


def test_mesh_creation():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    assert make_mesh(4).shape["data"] == 4


def test_sharded_dense_solve_matches_single_device(rng):
    x, y = _logistic(rng, n=100)  # 100 rows -> pads to 104 over 8 devices
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.3)

    plain = make_batch(DenseFeatures(jnp.asarray(x)), y)
    res1 = minimize_lbfgs(fun, jnp.zeros(6), args=(plain,), tol=1e-10)

    mesh = make_mesh()
    sharded = shard_batch(plain, mesh)
    assert sharded.labels.shape[0] == 104
    w0 = replicate(jnp.zeros(6), mesh)
    res2 = minimize_lbfgs(fun, w0, args=(sharded,), tol=1e-10)

    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-10))
    np.testing.assert_allclose(np.asarray(res2.x), np.asarray(res1.x),
                               atol=gold(1e-7, f32_floor=2e-3))


def test_sharded_csr_solve_matches_single_device(rng):
    n, d = 120, 10
    mat = sp.random(n, d, density=0.3, random_state=11, format="csr")
    y = (rng.random(n) < 0.5).astype(np.float64)
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.1)

    plain = make_batch(csr_from_scipy(mat, dtype=jnp.float64), y)
    res1 = minimize_tron(fun, jnp.zeros(d), args=(plain,), tol=1e-8)

    mesh = make_mesh()
    sharded = shard_batch(plain, mesh)
    res2 = minimize_tron(fun, replicate(jnp.zeros(d), mesh), args=(sharded,),
                         tol=1e-8)
    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-9))


def test_sharded_entity_blocks_match_single_device(rng):
    n, n_users = 200, 13  # 13 entities -> pads to 16 over 8 devices
    x = sp.csr_matrix(np.ones((n, 1)))
    users = rng.integers(0, n_users, n)
    y = (rng.random(n) < 0.4).astype(float)
    data = GameDataset.build(
        responses=y, feature_shards={"u": x},
        ids={"userId": users.astype(str)})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "u"), intercept_col=0)
    obj = GLMObjective(LogisticLoss)

    def solve_block(block):
        def fit(x_, y_, o_, w_):
            b = make_batch(DenseFeatures(x_), y_, o_, w_)
            return minimize_lbfgs(lambda c, bb: obj.value(c, bb, 0.2),
                                  jnp.zeros(block.d_pad), args=(b,), tol=1e-9)
        return jax.vmap(fit)(block.x, block.labels, block.offsets,
                             block.weights)

    mesh = make_mesh()
    for block in ds.blocks:
        res1 = solve_block(block)
        sblock = shard_block(block, mesh, sentinel_row=ds.n_rows)
        assert sblock.num_entities % 8 == 0
        res2 = solve_block(sblock)
        e = block.num_entities
        np.testing.assert_allclose(np.asarray(res2.x[:e]),
                                   np.asarray(res1.x),
                                   atol=gold(1e-7, f32_floor=2e-3))
        # padded entities solve to zero coefficients (pure L2)
        np.testing.assert_allclose(np.asarray(res2.x[e:]), 0.0, atol=gold(1e-12))


def test_scatter_from_sharded_blocks(rng):
    """Scores scattered from sharded blocks equal the unsharded scatter."""
    n, n_users = 150, 11
    x = sp.csr_matrix(rng.normal(0, 1, (n, 3)))
    users = rng.integers(0, n_users, n)
    data = GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"u": x}, ids={"userId": users.astype(str)})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "u"))
    mesh = make_mesh()

    margins, coefs = [], []
    for block in ds.blocks:
        c = jnp.asarray(rng.normal(0, 1, (block.num_entities, block.d_pad)))
        coefs.append(c)
        m = block.local_margins(c)
        margins.append(jnp.where(block.row_ids < ds.n_rows, m, 0.0))
    base = np.asarray(ds.scatter_scores(margins, [None] * len(ds.blocks)))

    scores = jnp.zeros((ds.n_rows + 1,))
    for block, c in zip(ds.blocks, coefs):
        sb = shard_block(block, mesh, sentinel_row=ds.n_rows)
        cpad = jnp.zeros((sb.num_entities, sb.d_pad)).at[
            : block.num_entities].set(c)
        m = sb.local_margins(cpad)
        m = jnp.where(sb.row_ids < ds.n_rows, m, 0.0)
        scores = scores.at[sb.row_ids.reshape(-1)].add(m.reshape(-1))
    np.testing.assert_allclose(np.asarray(scores[:-1]), base, atol=gold(1e-10))


def test_feature_dim_sharded_solve_matches_single_device(rng):
    """Coefficient-sharded mode (SURVEY §5 feature-dimension sharding):
    X columns + coefficients shard over the mesh, margins psum; result
    must match the replicated solve exactly."""
    from photon_ml_tpu.parallel import (
        shard_batch_feature_dim,
        shard_coef,
        unpad_coef,
    )

    x, y = _logistic(rng, n=60, d=13)  # d=13 pads to 16 over 8 devices
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.3)

    plain = make_batch(DenseFeatures(jnp.asarray(x)), y)
    res1 = minimize_lbfgs(fun, jnp.zeros(13), args=(plain,), tol=1e-10)

    mesh = make_mesh()
    sharded = shard_batch_feature_dim(plain, mesh)
    assert sharded.features.x.shape == (60, 16)
    w0 = shard_coef(jnp.zeros(13), mesh)
    assert w0.shape == (16,)
    res2 = minimize_lbfgs(fun, w0, args=(sharded,), tol=1e-10)

    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-10))
    w = unpad_coef(res2.x, 13)
    np.testing.assert_allclose(np.asarray(w), np.asarray(res1.x),
                               atol=gold(1e-7, f32_floor=2e-3))
    # Padded coordinates never moved.
    np.testing.assert_array_equal(np.asarray(res2.x)[13:], 0.0)


def test_2d_mesh_rows_and_features_sharded(rng):
    """Rows over 'data' x features over 'model' on a 4x2 mesh — both axes
    padded, solution identical to single-device."""
    from photon_ml_tpu.parallel import (
        make_mesh_2d,
        shard_batch_feature_dim,
        shard_coef,
        unpad_coef,
    )

    x, y = _logistic(rng, n=42, d=5)  # rows pad to 44, cols to 6
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.5)

    plain = make_batch(DenseFeatures(jnp.asarray(x)), y)
    res1 = minimize_lbfgs(fun, jnp.zeros(5), args=(plain,), tol=1e-10)

    mesh = make_mesh_2d(4, 2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    sharded = shard_batch_feature_dim(plain, mesh, col_axis="model",
                                      row_axis="data")
    assert sharded.features.x.shape == (44, 6)
    assert sharded.labels.shape == (44,)
    w0 = shard_coef(jnp.zeros(5), mesh, axis="model")
    res2 = minimize_lbfgs(fun, w0, args=(sharded,), tol=1e-10)

    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-10))
    np.testing.assert_allclose(np.asarray(unpad_coef(res2.x, 5)),
                               np.asarray(res1.x),
                               atol=gold(1e-7, f32_floor=2e-3))


def test_csr_feature_dim_sharded_solve_matches_single_device(rng):
    """The sparse huge-d mode: nnz routed into per-device column blocks,
    coefficients sharded to match — NO densification anywhere. Solution
    identical to the plain single-device CSR solve, and the per-device
    buffers provably hold only a slice (1/8 of blocks, 1/8 of coef)."""
    from photon_ml_tpu.parallel import (
        shard_batch_feature_dim,
        shard_coef,
        unpad_coef,
    )

    n, d = 80, 21  # d pads to 24 = 8 blocks x 3
    mat = sp.random(n, d, density=0.3, random_state=3, format="csr")
    mat.data[:] = rng.normal(0, 1, mat.nnz)
    y = (rng.random(n) < 0.5).astype(np.float64)
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.3)

    plain = make_batch(csr_from_scipy(mat, dtype=jnp.float64), y)
    res1 = minimize_lbfgs(fun, jnp.zeros(d), args=(plain,), tol=1e-10)

    mesh = make_mesh()
    sharded = shard_batch_feature_dim(plain, mesh)  # auto-routes CSR
    feats = sharded.features
    assert feats.num_blocks == 8 and feats.block_size == 3
    # Load-bearing sharding: each device holds ONE column block of the nnz
    # stream and 1/8 of the coefficients — never the full feature space.
    (shard0,) = {s.data.shape
                 for s in feats.values.addressable_shards}
    assert shard0 == (1, feats.values.shape[1])
    w0 = shard_coef(jnp.zeros(d), mesh)
    assert w0.shape == (24,)
    assert {s.data.shape for s in w0.addressable_shards} == {(3,)}

    res2 = minimize_lbfgs(fun, w0, args=(sharded,), tol=1e-10)
    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-10))
    np.testing.assert_allclose(np.asarray(unpad_coef(res2.x, d)),
                               np.asarray(res1.x),
                               atol=gold(1e-7, f32_floor=2e-3))
    # Padded coordinates never moved.
    np.testing.assert_array_equal(np.asarray(res2.x)[d:], 0.0)


def test_blocked_csr_products_match_dense(rng):
    from photon_ml_tpu.ops.features import blocked_csr_from_scipy

    n, d, kb = 30, 14, 4  # pads to 16 = 4 blocks x 4
    mat = sp.random(n, d, density=0.4, random_state=5, format="csr")
    mat.data[:] = rng.normal(0, 1, mat.nnz)
    feats = blocked_csr_from_scipy(mat, kb, dtype=jnp.float64)
    dense = np.zeros((n, feats.n_features))
    dense[:, :d] = mat.toarray()
    v = rng.normal(0, 1, feats.n_features)
    u = rng.normal(0, 1, n)
    tol = gold(1e-10, f32_floor=1e-4)
    np.testing.assert_allclose(np.asarray(feats.matvec(jnp.asarray(v))),
                               dense @ v, rtol=tol)
    np.testing.assert_allclose(np.asarray(feats.rmatvec(jnp.asarray(u))),
                               u @ dense, rtol=tol)
    np.testing.assert_allclose(
        np.asarray(feats.row_sq_matvec(jnp.asarray(v))),
        (dense * dense) @ v, rtol=tol)
    np.testing.assert_allclose(
        np.asarray(feats.sq_rmatvec(jnp.asarray(u))),
        u @ (dense * dense), rtol=tol)


def test_blocked_ell_products_match_dense(rng):
    """Dual-ELL (gather-only sparse layout — TPU scatter-add measured
    ~100x off roofline, see ops/features.py BlockedEllFeatures)."""
    from photon_ml_tpu.ops.features import blocked_ell_from_scipy

    for kb in (1, 4):
        n, d = 30, 14
        mat = sp.random(n, d, density=0.4, random_state=5, format="csr")
        mat.data[:] = rng.normal(0, 1, mat.nnz)
        feats = blocked_ell_from_scipy(mat, kb, dtype=jnp.float64)
        dense = np.zeros((n, feats.n_features))
        dense[:, :d] = mat.toarray()
        v = rng.normal(0, 1, feats.n_features)
        u = rng.normal(0, 1, n)
        tol = gold(1e-10, f32_floor=1e-4)
        np.testing.assert_allclose(
            np.asarray(feats.matvec(jnp.asarray(v))), dense @ v, rtol=tol)
        np.testing.assert_allclose(
            np.asarray(feats.rmatvec(jnp.asarray(u))), u @ dense,
            rtol=tol)
        np.testing.assert_allclose(
            np.asarray(feats.row_sq_matvec(jnp.asarray(v))),
            (dense * dense) @ v, rtol=tol)
        np.testing.assert_allclose(
            np.asarray(feats.sq_rmatvec(jnp.asarray(u))),
            u @ (dense * dense), rtol=tol)


def test_bucketed_ell_products_match_dense(rng):
    """Degree-bucketed dual-ELL: products agree with dense on skewed
    degree distributions, empty rows/columns included."""
    from photon_ml_tpu.ops.features import bucketed_ell_from_scipy

    n, d = 60, 40
    mat = sp.random(n, d, density=0.25, random_state=7, format="lil")
    mat[:, 5] = rng.normal(0, 1, (n, 1))  # heavy column
    mat[7, :] = rng.normal(0, 1, (1, d))  # heavy row
    mat[:, 3] = 0.0  # empty column (after the heavy-row write)
    mat[11, :] = 0.0  # empty row (after the heavy-column write)
    mat = mat.tocsr()
    mat.eliminate_zeros()
    coo = mat.tocoo()
    assert 3 not in coo.col and 11 not in coo.row  # degree-0 paths real
    for max_groups in (1, 3, 8):
        feats = bucketed_ell_from_scipy(mat, max_groups=max_groups,
                                        dtype=jnp.float64)
        assert feats.shape == (n, d)
        dense = mat.toarray()
        v = rng.normal(0, 1, d)
        u = rng.normal(0, 1, n)
        tol = gold(1e-10, f32_floor=1e-4)
        np.testing.assert_allclose(
            np.asarray(jax.jit(feats.matvec)(jnp.asarray(v))), dense @ v,
            rtol=tol, atol=1e-12)
        np.testing.assert_allclose(
            np.asarray(jax.jit(feats.rmatvec)(jnp.asarray(u))), u @ dense,
            rtol=tol, atol=1e-12)
        np.testing.assert_allclose(
            np.asarray(feats.row_sq_matvec(jnp.asarray(v))),
            (dense * dense) @ v, rtol=tol, atol=1e-12)
        np.testing.assert_allclose(
            np.asarray(feats.sq_rmatvec(jnp.asarray(u))),
            u @ (dense * dense), rtol=tol, atol=1e-12)
    # bucketing packs tighter than flat-width ELL on skewed degrees
    from photon_ml_tpu.ops.features import blocked_ell_from_scipy

    flat = blocked_ell_from_scipy(mat, 1, dtype=jnp.float64)
    flat_slots = flat.vals_r.size + flat.vals_c.size
    assert bucketed_ell_from_scipy(mat, 8).num_slots < flat_slots


def test_bucketed_ell_solve_matches_csr(rng):
    """A GLM solve over the bucketed-ELL layout reproduces the CSR solve."""
    from photon_ml_tpu.ops.features import bucketed_ell_from_scipy

    n, d = 80, 21
    mat = sp.random(n, d, density=0.3, random_state=3, format="csr")
    mat.data[:] = rng.normal(0, 1, mat.nnz)
    y = (rng.random(n) < 0.5).astype(np.float64)
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.3)

    plain = make_batch(csr_from_scipy(mat, dtype=jnp.float64), y)
    res1 = minimize_lbfgs(fun, jnp.zeros(d), args=(plain,), tol=1e-10)
    bell = bucketed_ell_from_scipy(mat, dtype=jnp.float64)
    res2 = minimize_lbfgs(fun, jnp.zeros(d), args=(make_batch(bell, y),),
                          tol=1e-10)
    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-10))
    np.testing.assert_allclose(np.asarray(res2.x), np.asarray(res1.x),
                               atol=gold(1e-7, f32_floor=2e-3))


def test_blocked_ell_solve_matches_csr(rng):
    """A GLM solve over the dual-ELL layout reproduces the CSR solve."""
    from photon_ml_tpu.ops.features import blocked_ell_from_scipy

    n, d = 80, 21
    mat = sp.random(n, d, density=0.3, random_state=3, format="csr")
    mat.data[:] = rng.normal(0, 1, mat.nnz)
    y = (rng.random(n) < 0.5).astype(np.float64)
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.3)

    plain = make_batch(csr_from_scipy(mat, dtype=jnp.float64), y)
    res1 = minimize_lbfgs(fun, jnp.zeros(d), args=(plain,), tol=1e-10)
    ell = blocked_ell_from_scipy(mat, 4, dtype=jnp.float64)
    eb = make_batch(ell, y)
    res2 = minimize_lbfgs(fun, jnp.zeros(ell.n_features), args=(eb,),
                          tol=1e-10)
    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-10))
    np.testing.assert_allclose(np.asarray(res2.x)[:d], np.asarray(res1.x),
                               atol=gold(1e-7, f32_floor=2e-3))


def test_ell_feature_dim_sharded_solve_matches_single_device(rng):
    """The dual-ELL layout shards over the mesh like blocked CSR: one
    column block (row-major AND col-major copies) per device."""
    from photon_ml_tpu.ops.features import blocked_ell_from_scipy
    from photon_ml_tpu.parallel import (
        shard_batch_feature_dim,
        shard_coef,
        unpad_coef,
    )

    n, d = 80, 21
    mat = sp.random(n, d, density=0.3, random_state=3, format="csr")
    mat.data[:] = rng.normal(0, 1, mat.nnz)
    y = (rng.random(n) < 0.5).astype(np.float64)
    obj = GLMObjective(LogisticLoss)
    fun = lambda w, b: obj.value(w, b, 0.3)

    plain = make_batch(csr_from_scipy(mat, dtype=jnp.float64), y)
    res1 = minimize_lbfgs(fun, jnp.zeros(d), args=(plain,), tol=1e-10)

    mesh = make_mesh()
    ell = blocked_ell_from_scipy(mat, 8, dtype=jnp.float64)
    sharded = shard_batch_feature_dim(make_batch(ell, y), mesh)
    sf = sharded.features
    assert {s.data.shape[0] for s in sf.vals_r.addressable_shards} == {1}
    assert {s.data.shape[0] for s in sf.vals_c.addressable_shards} == {1}
    w0 = shard_coef(jnp.zeros(d), mesh)
    res2 = minimize_lbfgs(fun, w0, args=(sharded,), tol=1e-10)
    np.testing.assert_allclose(float(res2.value), float(res1.value),
                               rtol=gold(1e-10))
    np.testing.assert_allclose(np.asarray(unpad_coef(res2.x, d)),
                               np.asarray(res1.x),
                               atol=gold(1e-7, f32_floor=2e-3))


def test_csr_feature_dim_sharding_rejects_row_axis(rng):
    import pytest as _pytest

    from photon_ml_tpu.parallel import shard_batch_csr_feature_dim

    n, d = 20, 6
    mat = sp.random(n, d, density=0.5, random_state=3, format="csr")
    y = (rng.random(n) < 0.5).astype(np.float64)
    batch = make_batch(csr_from_scipy(mat, dtype=jnp.float64), y)
    with _pytest.raises(ValueError, match="column"):
        shard_batch_csr_feature_dim(batch, make_mesh(), row_axis="data")


def test_bf16_feature_storage_solve_parity(rng):
    """bfloat16 feature storage (f32 accumulation) reproduces the f32
    solve to bf16-resolution tolerances — the validation recipe from
    docs/F32_PARITY.md applied to the storage-dtype axis."""
    from photon_ml_tpu.ops.features import features_to_device
    from photon_ml_tpu.optimization.glm_lbfgs import minimize_lbfgs_glm

    n, d = 4000, 30
    x = rng.normal(0, 1, (n, d)).astype(np.float32)
    x[:, 0] = 1.0
    w_true = rng.normal(0, 0.5, d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
    obj = GLMObjective(LogisticLoss)

    f32 = features_to_device(x)
    bf16 = features_to_device(x, storage_dtype=jnp.bfloat16)
    assert bf16.x.dtype == jnp.bfloat16
    r32 = minimize_lbfgs_glm(obj, make_batch(f32, y),
                             np.zeros(d, np.float32), 1e-2, tol=1e-8)
    r16 = minimize_lbfgs_glm(obj, make_batch(bf16, y),
                             np.zeros(d, np.float32), 1e-2, tol=1e-8)
    # margins/gradients carry bf16's ~3 decimal digits; the solve still
    # lands within ~1% of the f32 optimum in both value and coefficients
    assert r16.x.dtype == r32.x.dtype  # accumulation dtype, not storage
    np.testing.assert_allclose(float(r16.value), float(r32.value),
                               rtol=2e-3)
    np.testing.assert_allclose(np.asarray(r16.x), np.asarray(r32.x),
                               atol=3e-2, rtol=3e-2)


def test_bucketed_ell_power_law_degrees(rng):
    """Heavy-tailed (power-law) column degrees: the DP bucketing keeps
    slot count near true nnz and products stay exact."""
    from photon_ml_tpu.ops.features import bucketed_ell_from_scipy

    n, d = 400, 600
    # column popularity ~ zipf: a few dense columns, a long sparse tail
    col_p = 1.0 / np.arange(1, d + 1) ** 1.2
    col_p /= col_p.sum()
    nnz = 12_000
    rows = rng.integers(0, n, nnz)
    cols = rng.choice(d, size=nnz, p=col_p)
    vals = rng.normal(0, 1, nnz)
    mat = sp.coo_matrix((vals, (rows, cols)), shape=(n, d)).tocsr()
    mat.sum_duplicates()

    feats = bucketed_ell_from_scipy(mat, dtype=jnp.float64)
    dense = mat.toarray()
    # padding bounded: < 40% overhead even with zipf degrees (flat-width
    # ELL would pad every column to the max degree, >10x here)
    assert feats.num_slots < 2 * mat.nnz * 1.4
    v = rng.normal(0, 1, d)
    u = rng.normal(0, 1, n)
    np.testing.assert_allclose(np.asarray(feats.matvec(jnp.asarray(v))),
                               dense @ v, rtol=gold(1e-10, f32_floor=1e-4),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(feats.rmatvec(jnp.asarray(u))),
                               u @ dense, rtol=gold(1e-10, f32_floor=1e-4),
                               atol=1e-12)


def test_estimator_feature_sharded_fixed_effect(rng):
    """GameEstimator with FixedEffectSpec(feature_sharding=True) over a
    mesh matches the unsharded fit (2-D data x model mesh)."""
    import scipy.sparse as sp

    from photon_ml_tpu.estimators.game_estimator import (
        FixedEffectSpec,
        GameEstimator,
    )
    from photon_ml_tpu.data.game_data import GameDataset
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
    )
    from photon_ml_tpu.parallel import make_mesh_2d
    from photon_ml_tpu.types import TaskType

    n, d = 90, 10
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    w = rng.normal(0, 1, d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    data = GameDataset.build(responses=y,
                             feature_shards={"g": sp.csr_matrix(x)})
    cfg = GLMOptimizationConfiguration(max_iterations=40, tolerance=1e-9,
                                       regularization_weight=1.0)

    def fit(mesh, feature_sharding):
        est = GameEstimator(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinate_specs=[FixedEffectSpec(
                name="f", feature_shard_id="g", configs=[cfg],
                feature_sharding=feature_sharding)],
            mesh=mesh)
        results = est.fit(data, seed=0)
        m = results[0][1].model.get_model("f")
        return np.asarray(m.glm.coefficients.means)

    plain = fit(None, False)
    sharded = fit(make_mesh_2d(4, 2), True)
    assert sharded.shape == (d,)  # models stay at the true feature count
    np.testing.assert_allclose(sharded, plain, atol=2e-4)
