"""Avro codec + data reader + model persistence round trips
(reference: AvroUtilsTest, ModelProcessingUtilsTest patterns)."""

import numpy as np
import jax.numpy as jnp
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.avro_reader import (
    read_game_dataset,
    read_labeled_points,
)
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import (
    read_container,
    write_container,
    container_schema,
)
from photon_ml_tpu.io.model_io import (
    RandomEffectModelSnapshot,
    glm_from_avro_record,
    glm_to_avro_record,
    load_game_model,
    save_game_model,
    write_text_model,
)
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    LogisticRegressionModel,
    MatrixFactorizationModel,
)
from photon_ml_tpu.types import TaskType


@pytest.fixture(params=["native", "python"])
def ingest_mode(request, monkeypatch):
    """Run ingest tests through BOTH the native C fast path and the
    pure-python fallback so their behavior (values AND error surfaces)
    cannot drift apart."""
    import photon_ml_tpu.native as nat

    if request.param == "python":
        monkeypatch.setattr(nat, "_loaded", True)
        monkeypatch.setattr(nat, "_module", None)
    elif nat.load_avro_native() is None:
        pytest.skip("no C compiler available for the native decoder")
    return request.param


def _examples():
    return [
        {"uid": "r1", "label": 1.0,
         "features": [{"name": "f1", "term": None, "value": 0.5},
                      {"name": "f2", "term": "t", "value": -1.0}],
         "weight": 2.0, "offset": 0.1,
         "metadataMap": {"userId": "alice", "itemId": "x"}},
        {"uid": "r2", "label": 0.0,
         "features": [{"name": "f1", "term": None, "value": 1.5}],
         "weight": None, "offset": None,
         "metadataMap": {"userId": "bob", "itemId": "x"}},
    ]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_round_trip(tmp_path, codec):
    p = tmp_path / "data.avro"
    write_container(p, schemas.TRAINING_EXAMPLE, _examples(), codec=codec)
    back = list(read_container(p))
    assert back == [
        {**e, "weight": e["weight"], "offset": e["offset"]}
        for e in _examples()]
    assert container_schema(p)["name"] == "TrainingExampleAvro"


def test_container_multi_block(tmp_path):
    p = tmp_path / "big.avro"
    recs = [{"uid": None, "label": float(i),
             "features": [{"name": f"f{i % 50}", "term": None,
                           "value": i * 0.5}],
             "weight": None, "offset": None, "metadataMap": None}
            for i in range(5000)]
    write_container(p, schemas.TRAINING_EXAMPLE, recs, sync_interval=1024)
    back = list(read_container(p))
    assert len(back) == 5000
    assert back[4321]["label"] == 4321.0


def test_read_labeled_points(tmp_path, ingest_mode):
    p = tmp_path / "train.avro"
    write_container(p, schemas.TRAINING_EXAMPLE, _examples())
    mat, y, off, w, uids, imap = read_labeled_points(p)
    assert mat.shape == (2, 3)  # f1, f2:t, intercept
    assert len(imap) == 3
    np.testing.assert_allclose(y, [1.0, 0.0])
    np.testing.assert_allclose(off, [0.1, 0.0])
    np.testing.assert_allclose(w, [2.0, 1.0])
    assert uids == ["r1", "r2"]
    i1 = imap.get_index(feature_key("f1"))
    np.testing.assert_allclose(mat.toarray()[:, i1], [0.5, 1.5])
    np.testing.assert_allclose(mat.toarray()[:, imap.intercept_index], 1.0)


def test_read_game_dataset(tmp_path, ingest_mode):
    p = tmp_path / "game.avro"
    write_container(p, schemas.TRAINING_EXAMPLE, _examples())
    data, shard_maps = read_game_dataset(p, id_types=["userId", "itemId"])
    assert data.num_rows == 2
    assert set(shard_maps) == {"global"}
    assert data.id_columns["userId"].vocabulary.tolist() == ["alice", "bob"]
    with pytest.raises(ValueError, match="missing id type"):
        read_game_dataset(p, id_types=["queryId"])


def test_glm_avro_record_round_trip():
    imap = IndexMap.from_name_terms([("a", ""), ("b", "t")],
                                    add_intercept=True)
    means = jnp.asarray([1.5, 0.0, -0.25])
    variances = jnp.asarray([0.1, 0.2, 0.3])
    glm = LogisticRegressionModel(Coefficients(means, variances))
    rec = glm_to_avro_record("m1", glm, imap)
    assert rec["modelClass"] == "LogisticRegressionModel"
    # zero coefficient omitted
    assert len(rec["means"]) == 2
    mid, back = glm_from_avro_record(rec, imap)
    assert mid == "m1"
    np.testing.assert_allclose(np.asarray(back.coefficients.means),
                               [1.5, 0.0, -0.25])
    assert isinstance(back, LogisticRegressionModel)


def test_text_model_format(tmp_path):
    imap = IndexMap.from_name_terms([("age", ""), ("f", "x")],
                                    add_intercept=True)
    glm = LogisticRegressionModel(
        Coefficients(jnp.asarray([1.0, 2.0, -0.5])))
    out = tmp_path / "model.txt"
    write_text_model(out, glm, imap, reg_weight=10.0)
    lines = out.read_text().strip().split("\n")
    assert len(lines) == 3
    cols = lines[0].split("\t")
    assert len(cols) == 4 and cols[3] == "10.0"


def test_game_model_save_load_round_trip(tmp_path, rng):
    imap_g = IndexMap.from_name_terms([("x1", ""), ("x2", "")],
                                      add_intercept=True)
    imap_u = IndexMap.from_name_terms([], add_intercept=True)
    fe = FixedEffectModel(
        LogisticRegressionModel(
            Coefficients(jnp.asarray([0.5, -1.0, 0.25]))), "global")
    re = RandomEffectModelSnapshot(
        "userId", "user",
        sp.csr_matrix(np.asarray([[0.7], [-0.3]])),
        np.asarray(["alice", "bob"]))
    mf = MatrixFactorizationModel(
        "userId", "itemId",
        jnp.asarray(rng.normal(0, 1, (2, 3))),
        jnp.asarray(rng.normal(0, 1, (2, 3))),
        np.asarray(["alice", "bob"]), np.asarray(["x", "y"]))
    gm = GameModel({"fixed": fe, "perUser": re, "mf": mf},
                   TaskType.LOGISTIC_REGRESSION)
    root = tmp_path / "model"
    save_game_model(root, gm, {"global": imap_g, "user": imap_u})
    assert (root / "fixed-effect" / "fixed" / "coefficients" /
            "part-00000.avro").exists()
    assert (root / "random-effect" / "perUser" / "id-info").exists()

    back = load_game_model(root, {"global": imap_g, "user": imap_u})
    assert back.task_type == TaskType.LOGISTIC_REGRESSION
    np.testing.assert_allclose(
        np.asarray(back.get_model("fixed").glm.coefficients.means),
        [0.5, -1.0, 0.25])
    re2 = back.get_model("perUser")
    assert re2.vocabulary.tolist() == ["alice", "bob"]
    np.testing.assert_allclose(re2.matrix.toarray(), [[0.7], [-0.3]])
    mf2 = back.get_model("mf")
    np.testing.assert_allclose(np.asarray(mf2.row_factors),
                               np.asarray(mf.row_factors), rtol=1e-12)

    # Scores agree before/after the round trip on a real dataset.
    n = 4
    data_mat = sp.csr_matrix(
        np.hstack([rng.normal(0, 1, (n, 2)), np.ones((n, 1))]))
    user_mat = sp.csr_matrix(np.ones((n, 1)))
    from photon_ml_tpu.data.game_data import GameDataset
    data = GameDataset.build(
        responses=np.zeros(n),
        feature_shards={"global": data_mat, "user": user_mat},
        ids={"userId": np.asarray(["alice", "bob", "carol", "alice"]),
             "itemId": np.asarray(["x", "y", "x", "z"])})
    np.testing.assert_allclose(back.score(data), gm.score(data), rtol=1e-6)
