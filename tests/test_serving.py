"""Streaming serving engine: score parity with the host path, bucket /
compile-cache discipline, micro-batch scatter, padded-row isolation, and
the vectorized vocab join. Reference scoring semantics are the same as
DeviceGameScorer's (ml/model/*Model.scala score paths); what is under test
here is the REQUEST-side machinery: shape bucketing, the executable cache,
and the featureize->H2D->score pipeline."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    LogisticRegressionModel,
    MatrixFactorizationModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    BucketLadder,
    ExecutableCache,
    StreamingGameScorer,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.vocab import SortedVocab, vocab_code_lookup

DT = jnp.float64


def _dataset(rng, n=60, d=6, n_users=7, n_items=5, user_names=None):
    x = rng.normal(0, 1, (n, d))
    x[:, -1] = 1.0
    if user_names is None:
        users = rng.integers(0, n_users, n).astype(str)
    else:
        users = np.asarray(user_names)
    items = rng.integers(0, n_items, n).astype(str)
    user_x = sp.csr_matrix(np.hstack(
        [rng.normal(0, 1, (n, 2)), np.ones((n, 1))]))
    return GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"global": sp.csr_matrix(x), "user": user_x},
        ids={"userId": users, "itemId": items})


def _game_model(rng, train):
    ds = build_random_effect_dataset(
        train, RandomEffectDataConfiguration("userId", "user"),
        intercept_col=2)
    re = RandomEffectModel.zeros_like_dataset(ds, dtype=DT)
    re = re.with_coefs([jnp.asarray(rng.normal(0, 1, np.asarray(c).shape))
                        for c in re.local_coefs])
    fe = FixedEffectModel(
        LogisticRegressionModel(Coefficients(
            jnp.asarray(rng.normal(0, 1, 6)))), "global")
    mf = MatrixFactorizationModel(
        "userId", "itemId",
        jnp.asarray(rng.normal(0, 1, (7, 3))),
        jnp.asarray(rng.normal(0, 1, (5, 3))),
        np.unique(train.id_columns["userId"].vocabulary),
        np.unique(train.id_columns["itemId"].vocabulary))
    return GameModel({"fixed": fe, "perUser": re, "mf": mf},
                     TaskType.LOGISTIC_REGRESSION)


@pytest.fixture
def engine_and_model(rng):
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    eng = StreamingGameScorer(gm, dtype=DT,
                              ladder=BucketLadder(min_rows=8, max_rows=64))
    return eng, gm


# -- parity ----------------------------------------------------------------

@pytest.mark.needs_f64
def test_engine_matches_host_scoring(engine_and_model, rng):
    eng, gm = engine_and_model
    req = _dataset(np.random.default_rng(5), n=37)
    np.testing.assert_allclose(eng.score(req), gm.score(req),
                               rtol=1e-10, atol=1e-10)


@pytest.mark.needs_f64
def test_engine_splits_oversized_requests(engine_and_model):
    eng, gm = engine_and_model
    req = _dataset(np.random.default_rng(7), n=150)  # > max_rows=64
    np.testing.assert_allclose(eng.score(req), gm.score(req),
                               rtol=1e-10, atol=1e-10)


@pytest.mark.needs_f64
def test_engine_micro_batch_scatters_per_request(engine_and_model):
    eng, gm = engine_and_model
    reqs = [_dataset(np.random.default_rng(i), n=k)
            for i, k in enumerate([5, 9, 17, 3, 70, 1])]
    outs = eng.score_many(reqs)
    assert len(outs) == len(reqs)
    for r, o in zip(reqs, outs):
        assert len(o) == r.num_rows
        np.testing.assert_allclose(o, gm.score(r), rtol=1e-10, atol=1e-10)
    # small requests genuinely shared dispatches
    assert eng.stats()["dispatches"] < len(reqs) + 1


@pytest.mark.needs_f64
def test_engine_stream_order_and_parity(engine_and_model):
    eng, gm = engine_and_model
    reqs = [_dataset(np.random.default_rng(10 + i), n=k)
            for i, k in enumerate([12, 33, 64, 2, 150])]
    outs = list(eng.score_stream(iter(reqs)))
    assert len(outs) == len(reqs)
    for r, o in zip(reqs, outs):
        np.testing.assert_allclose(o, gm.score(r), rtol=1e-10, atol=1e-10)


# -- edge cases ------------------------------------------------------------

def test_all_unknown_entities_score_re_and_mf_zero(rng):
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    # Drop the fixed effect so every score must be exactly 0.
    gm_re = GameModel({k: v for k, v in gm.models.items() if k != "fixed"},
                      TaskType.LOGISTIC_REGRESSION)
    eng = StreamingGameScorer(gm_re, dtype=DT,
                              ladder=BucketLadder(min_rows=8, max_rows=64))
    req = _dataset(np.random.default_rng(3), n=20,
                   user_names=["zz_unknown"] * 20)
    # unknown item ids too
    req = GameDataset.build(
        responses=req.responses,
        feature_shards=dict(req.feature_shards),
        ids={"userId": np.asarray(["zz_unknown"] * 20),
             "itemId": np.asarray(["qq_missing"] * 20)})
    np.testing.assert_allclose(eng.score(req), 0.0)
    np.testing.assert_allclose(gm_re.score(req), 0.0)


def test_zero_nnz_batch_scores_zero_fixed(rng):
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    eng = StreamingGameScorer(gm, dtype=DT,
                              ladder=BucketLadder(min_rows=8, max_rows=64))
    n = 11
    req = GameDataset.build(
        responses=np.zeros(n),
        feature_shards={"global": sp.csr_matrix((n, 6)),
                        "user": sp.csr_matrix((n, 3))},
        ids={"userId": np.asarray(["zz"] * n),
             "itemId": np.asarray(["qq"] * n)})
    # all-zero features + unknown entities -> exactly zero margins
    np.testing.assert_allclose(eng.score(req), 0.0)
    np.testing.assert_allclose(gm.score(req), 0.0)


def test_empty_request_returns_empty_without_dispatch(engine_and_model):
    eng, _ = engine_and_model
    empty = GameDataset.build(
        responses=np.zeros(0),
        feature_shards={"global": sp.csr_matrix((0, 6)),
                        "user": sp.csr_matrix((0, 3))},
        ids={"userId": np.asarray([], str), "itemId": np.asarray([], str)})
    before = eng.stats()["dispatches"]
    assert len(eng.score(empty)) == 0
    assert eng.stats()["dispatches"] == before
    outs = list(eng.score_stream([empty]))
    assert len(outs) == 1 and len(outs[0]) == 0


@pytest.mark.needs_f64
def test_bucket_boundary_padding_does_not_leak(rng):
    """Requests at an exact bucket size and one row over: scores must be
    identical to the host path row-for-row, and the evaluator metric over
    streamed scores must equal the full-batch metric (padded rows never
    reach scores or metrics)."""
    from photon_ml_tpu.evaluation import build_evaluator

    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    eng = StreamingGameScorer(gm, dtype=DT,
                              ladder=BucketLadder(min_rows=8, max_rows=64))
    for n in (8, 9, 16, 17, 64):
        req = _dataset(np.random.default_rng(n), n=n)
        got = eng.score(req)
        assert got.shape == (n,)
        np.testing.assert_allclose(got, gm.score(req),
                                   rtol=1e-10, atol=1e-10)
    # metric parity: stream in 3 uneven batches vs one host pass
    req = _dataset(np.random.default_rng(77), n=50)
    parts = [req.subset(np.arange(0, 13)), req.subset(np.arange(13, 45)),
             req.subset(np.arange(45, 50))]
    streamed = np.concatenate(list(eng.score_stream(parts)))
    ev = build_evaluator("AUC")
    assert ev.evaluate_dataset(streamed, req) == pytest.approx(
        ev.evaluate_dataset(gm.score(req), req), abs=1e-12)


# -- compile-cache discipline ---------------------------------------------

def test_executable_cache_counts_builds():
    cache = ExecutableCache()
    built = []
    for key in ["a", "b", "a", "a", "b", "c"]:
        cache.get_or_build(key, lambda k=key: built.append(k) or (lambda: k))
    assert cache.compilations == 3
    assert len(cache) == 3
    assert built == ["a", "b", "c"]


def test_compile_count_bounded_by_bucket_ladder(rng, tracing_guard):
    """50 random-size requests compile at most (distinct buckets + 1)
    executables, and re-scoring the same sizes compiles nothing new —
    asserted through the shared tracing_guard infrastructure (every
    executable the cache ever builds registers there; trace totals count
    actual XLA traces, not hand-rolled build increments)."""
    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    ladder = BucketLadder(min_rows=8, max_rows=64)
    eng = StreamingGameScorer(gm, dtype=DT, ladder=ladder,
                              tracing_guard=tracing_guard)
    sizes = np.random.default_rng(0).integers(1, 65, 50)
    reqs = [_dataset(np.random.default_rng(100 + i), n=int(n))
            for i, n in enumerate(sizes)]
    for r in reqs:
        eng.score(r)
    expected_keys = set()
    for r in reqs:
        nnz = tuple(int(r.feature_shards[s].nnz) for s in ("global", "user"))
        expected_keys.add(ladder.bucket_shape(r.num_rows, nnz))
    # Guard-asserted invariants: executables ever built (and their total
    # traces) bounded by the ladder, each bucket traced exactly once.
    eng.cache.assert_max_retraces(max_total=len(expected_keys) + 1,
                                  per_fn=1)
    assert eng.cache.total_traces() == eng.cache.compilations
    assert eng.stats()["traces"] == eng.stats()["compilations"]
    assert eng.stats()["entries"] == eng.cache.compilations
    before = eng.cache.total_traces()
    for r in reqs[:10]:
        eng.score(r)
    assert eng.cache.total_traces() == before
    # Teardown re-checks the bound declaratively via the fixture.
    tracing_guard.set_budget(len(expected_keys) + 1)


def test_tracing_guard_trips_on_per_call_bucket_eviction(rng,
                                                         tracing_guard):
    """Injected regression: evict the bucket entry before every dispatch
    (the exact failure the ExecutableCache exists to prevent). Each
    dispatch then rebuilds + retraces a fresh executable; the guard keeps
    evicted generations in its totals, so assert_max_retraces MUST trip
    even though the cache itself only ever holds one entry."""
    from photon_ml_tpu.utils.tracing_guard import RetraceError

    train = _dataset(rng, n=80)
    gm = _game_model(rng, train)
    eng = StreamingGameScorer(gm, dtype=DT,
                              ladder=BucketLadder(min_rows=8, max_rows=64),
                              tracing_guard=tracing_guard)
    orig = eng.cache.get_or_build

    def evict_then_build(key, build):
        eng.cache._entries.clear()  # bucket evicted per call
        return orig(key, build)

    eng.cache.get_or_build = evict_then_build
    req = _dataset(np.random.default_rng(11), n=16)
    for _ in range(6):  # same bucket shape every time: SHOULD be 1 compile
        eng.score(req)
    assert len(tracing_guard) == 6  # every evicted generation tracked
    with pytest.raises(RetraceError, match="exceed budget"):
        eng.cache.assert_max_retraces(max_total=2)
    with pytest.raises(RetraceError):
        tracing_guard.assert_max_retraces(max_total=2)


def test_bucket_ladder_shapes():
    ladder = BucketLadder(min_rows=16, max_rows=4096)
    assert ladder.rows_bucket(1) == 16
    assert ladder.rows_bucket(16) == 16
    assert ladder.rows_bucket(17) == 32
    assert ladder.rows_bucket(4096) == 4096
    with pytest.raises(ValueError):
        ladder.rows_bucket(4097)
    assert ladder.nnz_bucket(0, 16) == 16  # zero-nnz stays a valid block
    assert ladder.nnz_bucket(33, 16) == 16 * 4  # width 3 -> 4
    assert ladder.num_row_buckets() == 9  # 16..4096


# -- vectorized vocab join -------------------------------------------------

def test_vocab_lookup_matches_dict_join(rng):
    vocab = np.unique(
        [f"ent{int(i)}" for i in rng.integers(0, 500, 200)])
    rng.shuffle(vocab)  # model vocab order is NOT sorted
    queries = np.asarray(
        [f"ent{int(i)}" for i in rng.integers(0, 1000, 300)])
    idx = {str(n): i for i, n in enumerate(vocab)}
    want = np.asarray([idx.get(str(n), -1) for n in queries], np.int64)
    got = vocab_code_lookup(vocab, queries)
    np.testing.assert_array_equal(got, want)
    assert (got == -1).any(), "test must cover unknown entities"
    # prebuilt form agrees and handles empty inputs
    sv = SortedVocab.build(vocab)
    np.testing.assert_array_equal(sv.codes_of(queries), want)
    assert vocab_code_lookup(vocab, np.asarray([], str)).size == 0
    assert (vocab_code_lookup(np.asarray([], str), queries) == -1).all()


def test_snapshot_densify_ceiling_rejects_at_construction():
    """A loaded random-effect snapshot too large to densify must raise
    the constructor-time TypeError contract (driver -> host fallback),
    never attempt the allocation."""
    from photon_ml_tpu.serving import kernels as sk

    class Snap:  # duck-typed io.model_io.RandomEffectModelSnapshot
        random_effect_type = "userId"
        feature_shard_id = "global"
        vocabulary = np.arange(3_000_000)
        matrix = sp.csr_matrix((3_000_000, 200_000))

    assert sk.is_re_snapshot(Snap())
    with pytest.raises(TypeError, match="densification ceiling"):
        sk.check_snapshot_densifiable(Snap(), np.float64)
    gm = GameModel({"perUser": Snap()}, TaskType.LOGISTIC_REGRESSION)
    with pytest.raises(TypeError, match="densification ceiling"):
        StreamingGameScorer(gm, dtype=DT)
    # comfortably-small snapshots stay densifiable
    class Small(Snap):
        vocabulary = np.arange(10)
        matrix = sp.csr_matrix(np.eye(10, 6))

    sk.check_snapshot_densifiable(Small(), np.float64)


def test_engine_rejects_unsupported_submodel(rng):
    class Exotic:
        pass

    gm = GameModel({"weird": Exotic()}, TaskType.LOGISTIC_REGRESSION)
    with pytest.raises(TypeError, match="cannot device-score"):
        StreamingGameScorer(gm)


def test_engine_rejects_missing_shard_and_wrong_width(engine_and_model):
    eng, _ = engine_and_model
    n = 4
    base = dict(responses=np.zeros(n),
                ids={"userId": np.asarray(["a"] * n),
                     "itemId": np.asarray(["b"] * n)})
    with pytest.raises(KeyError, match="missing feature shard"):
        eng.score(GameDataset.build(
            feature_shards={"global": sp.csr_matrix((n, 6))}, **base))
    with pytest.raises(ValueError, match="model expects"):
        eng.score(GameDataset.build(
            feature_shards={"global": sp.csr_matrix((n, 6)),
                            "user": sp.csr_matrix((n, 99))}, **base))
