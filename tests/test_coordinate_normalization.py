"""Normalization in coordinates: models are stored/scored in the original
space while solving in the normalized space — scores must be identical to an
unnormalized solve at the optimum (same problem, different parametrization).
"""

import numpy as np
import jax.numpy as jnp
import scipy.sparse as sp

from photon_ml_tpu.algorithm import CoordinateDescent, FixedEffectCoordinate
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.normalization import build_normalization_context
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.data.stats import BasicStatisticalSummary
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.types import TaskType


def test_fixed_effect_with_standardization_matches_plain(rng):
    n, d = 300, 5
    x = rng.normal(2.0, 3.0, (n, d))  # deliberately off-center, scaled
    x[:, -1] = 1.0
    w = rng.normal(0, 1, d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x - 2) @ w / 3))).astype(float)
    data = GameDataset.build(responses=y,
                             feature_shards={"s": sp.csr_matrix(x)})

    summary = BasicStatisticalSummary.compute(data.feature_shards["s"])
    norm = build_normalization_context("STANDARDIZATION", summary,
                                       intercept_id=d - 1)
    cfg = GLMOptimizationConfiguration(max_iterations=200, tolerance=1e-10)

    def fit(normalization):
        coord = FixedEffectCoordinate(
            name="f", data=data, feature_shard_id="s",
            task_type=TaskType.LOGISTIC_REGRESSION, config=cfg,
            normalization=normalization, dtype=jnp.float64)
        cd = CoordinateDescent({"f": coord}, TaskType.LOGISTIC_REGRESSION)
        res = cd.run(num_iterations=1)
        model = res.model.get_model("f")
        return np.asarray(coord.score(model)), np.asarray(
            model.glm.coefficients.means)

    s_norm, w_norm = fit(norm)
    s_plain, w_plain = fit(None)
    # Unregularized optimum is parametrization-invariant: same model.
    np.testing.assert_allclose(w_norm, w_plain, atol=5e-4)
    np.testing.assert_allclose(s_norm, s_plain, atol=5e-4)
    # Device scoring == host scoring (original space consistency).
    model = None  # re-fit to compare paths
    coord = FixedEffectCoordinate(
        name="f", data=data, feature_shard_id="s",
        task_type=TaskType.LOGISTIC_REGRESSION, config=cfg,
        normalization=norm, dtype=jnp.float64)
    cd = CoordinateDescent({"f": coord}, TaskType.LOGISTIC_REGRESSION)
    res = cd.run(num_iterations=1)
    fe = res.model.get_model("f")
    np.testing.assert_allclose(
        np.asarray(coord.score(fe)), fe.score_numpy(data), atol=1e-8)


def test_identity_projector_uses_full_feature_space(rng):
    n, d = 40, 6
    x = sp.random(n, d, density=0.3, random_state=5, format="csr")
    data = GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"s": x},
        ids={"userId": np.asarray(["a", "b"] * (n // 2))})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "s",
                                            projector_type="IDENTITY"))
    for b in ds.blocks:
        fidx = np.asarray(b.feat_idx)
        for e in range(b.num_entities):
            assert list(fidx[e][fidx[e] >= 0]) == list(range(d))


def test_random_projector_builds_latent_blocks(rng):
    data = GameDataset.build(
        responses=np.zeros(4),
        feature_shards={"s": sp.csr_matrix(np.ones((4, 2)))},
        ids={"userId": np.asarray(["a", "a", "b", "b"])})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "s",
                                            projector_type="RANDOM=2"))
    assert ds.projection is not None
    assert ds.projection.projected_space_dimension == 2
    assert ds.projection.original_space_dimension == 2


def test_random_effect_spec_normalization_through_estimator(rng):
    """GameEstimator grid training with a normalized + bounded random
    effect (RandomEffectSpec.normalization / bounds — the reference's
    RandomEffectOptimizationProblem normalization + constraintMap,
    RandomEffectOptimizationProblem.scala:105-125): the unregularized
    factor-normalized solve matches the plain solve (parametrization
    invariance), and bounds clamp original-space coefficients."""
    from photon_ml_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
    )
    from photon_ml_tpu.estimators.game_estimator import (
        GameEstimator,
        RandomEffectSpec,
    )

    n, d = 240, 4
    x = rng.normal(0, 1.0, (n, d))
    x *= np.array([1.0, 5.0, 0.4, 2.0])[None, :]
    x[:, 0] = 1.0
    w = np.array([0.2, 0.3, -1.5, 0.6])
    y = (rng.random(n) < 1 / (1 + np.exp(-x @ (w / np.array(
        [1.0, 5.0, 0.4, 2.0]))))).astype(float)
    data = GameDataset.build(
        responses=y,
        feature_shards={"s": sp.csr_matrix(x)},
        ids={"userId": np.asarray([f"u{i % 6}" for i in range(n)])})
    cfg = GLMOptimizationConfiguration(max_iterations=150, tolerance=1e-10)
    norm = build_normalization_context(
        "SCALE_WITH_STANDARD_DEVIATION",
        BasicStatisticalSummary.compute(data.feature_shards["s"]),
        intercept_id=0)

    def fit(normalization, lb=None, ub=None):
        est = GameEstimator(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinate_specs=[RandomEffectSpec(
                name="re",
                data_config=RandomEffectDataConfiguration(
                    random_effect_type="userId", feature_shard_id="s"),
                configs=[cfg], intercept_col=0,
                normalization=normalization,
                lower_bounds=lb, upper_bounds=ub)],
            dtype=jnp.float64)
        results = est.fit(data, seed=0)
        assert len(results) == 1
        model = results[0][1].model.get_model("re")
        return np.concatenate(
            [np.asarray(c) for c in model.local_coefs], axis=0)

    coefs_norm = fit(norm)
    coefs_plain = fit(None)
    # Unregularized optimum is parametrization-invariant (models are
    # stored in the original space either way).
    np.testing.assert_allclose(coefs_norm, coefs_plain, atol=2e-3)

    cap = 0.4
    coefs_box = fit(norm, lb=np.full(d, -cap), ub=np.full(d, cap))
    # Bounds clamp the SOLVE-SPACE coefficients (reference semantics:
    # the projected iterate is the normalized-space vector). Blocks'
    # local columns are the sorted global columns here (single shard,
    # all observed), so dividing by the global factors recovers w'.
    factors = np.asarray(norm.factors)
    solve_plain = coefs_plain[:, :d] / factors[None, :]
    solve_box = coefs_box[:, :d] / factors[None, :]
    assert (np.abs(solve_plain) > cap + 0.05).any(), \
        "test problem never activates the box"
    assert (np.abs(solve_box) <= cap + 1e-5).all()


def test_train_glm_bounds_clamp_solve_space(rng):
    """train_glm_models with normalization + box constraints: the box
    clamps the SOLVE-SPACE iterate — reference semantics (the Breeze
    iterate is the normalized-space vector, effectiveCoefficients =
    coef :* factors in ValueAndGradientAggregator.scala:100-120, and
    projectCoefficientsToHypercube clamps it raw at LBFGS.scala:77)."""
    from photon_ml_tpu.estimators.model_training import train_glm_models

    n, d = 400, 4
    x = rng.normal(0, 1.0, (n, d))
    x[:, 0] = 1.0
    x[:, 1] *= 10.0  # big scale -> factor ~0.1
    w_orig = np.array([0.1, 0.25, -1.4, 0.8])
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w_orig)))).astype(float)
    norm = build_normalization_context(
        "SCALE_WITH_STANDARD_DEVIATION",
        BasicStatisticalSummary.compute(sp.csr_matrix(x)),
        intercept_id=0)
    cap = 0.6
    trained = train_glm_models(
        sp.csr_matrix(x), y, TaskType.LOGISTIC_REGRESSION,
        regularization_weights=[0.01],
        normalization=norm,
        lower_bounds=np.full(d, -cap), upper_bounds=np.full(d, cap),
        max_iterations=150, tolerance=1e-10)
    coefs = np.asarray(trained[0].model.coefficients.means)
    solve_space = np.asarray(norm.model_to_normalized_space(
        jnp.asarray(coefs)))
    assert (np.abs(solve_space) <= cap + 1e-6).all(), solve_space
    # The box is ACTIVE: the strong coefficient (solve-space |w'|~1.4
    # unconstrained since std(col2)~1) clamps at the cap...
    assert np.isclose(np.abs(solve_space).max(), cap, atol=1e-3)
    # ...and the ORIGINAL-space coefficient on the scaled column 1
    # equals w'_1 * factor_1 — bounded by cap/std(col1) ~ 0.06, far
    # below the raw cap (the solve-space semantics made visible).
    std1 = float(np.std(x[:, 1]))
    assert np.abs(coefs[1]) <= cap / std1 * 1.05
