"""Normalization in coordinates: models are stored/scored in the original
space while solving in the normalized space — scores must be identical to an
unnormalized solve at the optimum (same problem, different parametrization).
"""

import numpy as np
import jax.numpy as jnp
import scipy.sparse as sp

from photon_ml_tpu.algorithm import CoordinateDescent, FixedEffectCoordinate
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.normalization import build_normalization_context
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.data.stats import BasicStatisticalSummary
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.types import TaskType


def test_fixed_effect_with_standardization_matches_plain(rng):
    n, d = 300, 5
    x = rng.normal(2.0, 3.0, (n, d))  # deliberately off-center, scaled
    x[:, -1] = 1.0
    w = rng.normal(0, 1, d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x - 2) @ w / 3))).astype(float)
    data = GameDataset.build(responses=y,
                             feature_shards={"s": sp.csr_matrix(x)})

    summary = BasicStatisticalSummary.compute(data.feature_shards["s"])
    norm = build_normalization_context("STANDARDIZATION", summary,
                                       intercept_id=d - 1)
    cfg = GLMOptimizationConfiguration(max_iterations=200, tolerance=1e-10)

    def fit(normalization):
        coord = FixedEffectCoordinate(
            name="f", data=data, feature_shard_id="s",
            task_type=TaskType.LOGISTIC_REGRESSION, config=cfg,
            normalization=normalization, dtype=jnp.float64)
        cd = CoordinateDescent({"f": coord}, TaskType.LOGISTIC_REGRESSION)
        res = cd.run(num_iterations=1)
        model = res.model.get_model("f")
        return np.asarray(coord.score(model)), np.asarray(
            model.glm.coefficients.means)

    s_norm, w_norm = fit(norm)
    s_plain, w_plain = fit(None)
    # Unregularized optimum is parametrization-invariant: same model.
    np.testing.assert_allclose(w_norm, w_plain, atol=5e-4)
    np.testing.assert_allclose(s_norm, s_plain, atol=5e-4)
    # Device scoring == host scoring (original space consistency).
    model = None  # re-fit to compare paths
    coord = FixedEffectCoordinate(
        name="f", data=data, feature_shard_id="s",
        task_type=TaskType.LOGISTIC_REGRESSION, config=cfg,
        normalization=norm, dtype=jnp.float64)
    cd = CoordinateDescent({"f": coord}, TaskType.LOGISTIC_REGRESSION)
    res = cd.run(num_iterations=1)
    fe = res.model.get_model("f")
    np.testing.assert_allclose(
        np.asarray(coord.score(fe)), fe.score_numpy(data), atol=1e-8)


def test_identity_projector_uses_full_feature_space(rng):
    n, d = 40, 6
    x = sp.random(n, d, density=0.3, random_state=5, format="csr")
    data = GameDataset.build(
        responses=(rng.random(n) < 0.5).astype(float),
        feature_shards={"s": x},
        ids={"userId": np.asarray(["a", "b"] * (n // 2))})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "s",
                                            projector_type="IDENTITY"))
    for b in ds.blocks:
        fidx = np.asarray(b.feat_idx)
        for e in range(b.num_entities):
            assert list(fidx[e][fidx[e] >= 0]) == list(range(d))


def test_random_projector_builds_latent_blocks(rng):
    data = GameDataset.build(
        responses=np.zeros(4),
        feature_shards={"s": sp.csr_matrix(np.ones((4, 2)))},
        ids={"userId": np.asarray(["a", "a", "b", "b"])})
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "s",
                                            projector_type="RANDOM=2"))
    assert ds.projection is not None
    assert ds.projection.projected_space_dimension == 2
    assert ds.projection.original_space_dimension == 2
