"""Batched λ-grid streamed solves (PR 16): the one-pass sweep contract.

- G=1 batched DELEGATES to the scalar streamed solver — model bytes
  identical (the bitwise gate holds by construction).
- G>1 batched L-BFGS reproduces the sequential per-λ sweep's iteration
  structure (same counts/reasons) with per-coefficient agreement to
  accumulation tolerance, and both sweeps select the SAME model.
- Feature passes per sweep are independent of G (the whole point: one
  streamed epoch advances every grid point).
- Per-λ observability survives batching: convergence rings keep the
  sequential ring structure, a diverging row raises
  SolverDivergedError carrying ITS λ / grid row / trace id, and rows
  other than the poisoned one keep finite-only rings.
- Compile counts stay inside the grid kernel budgets and are flat
  across λ values (λ is a traced argument, G is the only shape knob).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.algorithm.coordinate_descent import (
    CoordinateDescentResult,
)
from photon_ml_tpu.algorithm.coordinates import (
    StreamingFixedEffectCoordinate,
    grid_batchable,
    solve_fixed_effect_grid,
)
from photon_ml_tpu.data.shard_cache import DeviceShardCache
from photon_ml_tpu.estimators.game_estimator import select_best_result
from photon_ml_tpu.ops.glm_objective import GLMObjective
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.sharded_objective import ShardedGLMObjective
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    ConvergenceRing,
    SolverDivergedError,
)
from photon_ml_tpu.optimization.glm_lbfgs import (
    minimize_lbfgs_glm_grid_streaming,
    minimize_lbfgs_glm_streaming,
)
from photon_ml_tpu.optimization.tron import (
    minimize_tron_grid_streaming,
    minimize_tron_streaming,
)
from photon_ml_tpu.types import TaskType

from tests.test_shard_cache import FakeStream


@pytest.fixture
def problem(rng):
    n, d = 403, 23
    X = sp.random(n, d, density=0.15, random_state=7, format="csr")
    X.data[:] = rng.normal(0, 1, X.nnz)
    y = (rng.random(n) < 0.5).astype(float)
    off = rng.normal(0, 0.1, n)
    w = rng.gamma(1.0, 1.0, n)
    return X, y, off, w


def _sharded(X, y, off, w, batch_rows=96, budget=None):
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, batch_rows, off, w), "g",
        hbm_budget_bytes=budget)
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    return ShardedGLMObjective(obj, cache)


def _bits(x):
    return np.asarray(x).tobytes()


def _x0s(G, d):
    return jnp.zeros((G, d), jnp.float32)


# -- bitwise gate -----------------------------------------------------------


@pytest.mark.parametrize("grid_fn,scalar_fn", [
    (minimize_lbfgs_glm_grid_streaming, minimize_lbfgs_glm_streaming),
    (minimize_tron_grid_streaming, minimize_tron_streaming),
])
def test_g1_batched_bitwise_identical(problem, grid_fn, scalar_fn):
    """G=1 batched == scalar streamed solver, bit for bit (delegation:
    there is no '1-wide vmap' variant to drift — XLA's batched reduces
    are not prefix-stable, so the gate holds by construction)."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    ref = scalar_fn(sobj, jnp.zeros(d, jnp.float32),
                    np.float32(0.7), max_iter=12)
    holder = []
    [res] = grid_fn(sobj, _x0s(1, d), np.asarray([0.7], np.float32),
                    max_iter=12, margins_out=holder)
    assert _bits(res.x) == _bits(ref.x)
    assert _bits(res.value) == _bits(ref.value)
    assert res.iterations == ref.iterations
    assert res.reason == ref.reason
    # margins come back grid-shaped even on the delegated path
    assert all(z.ndim == 2 and z.shape[0] == 1 for z in holder)


# -- G>1 parity + selection -------------------------------------------------


def test_grid_lbfgs_matches_sequential_and_selects_same(problem):
    """Batched L-BFGS over G=3 λ rows: per-row iteration counts and
    convergence reasons equal the sequential sweep's, coefficients agree
    to accumulation tolerance, and the lowest-objective row is the same
    model either way (selection parity, the G>1 acceptance bound)."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    l2s = np.asarray([0.3, 3.0, 30.0], np.float32)
    seq = [minimize_lbfgs_glm_streaming(
        sobj, jnp.zeros(d, jnp.float32), l2, max_iter=25) for l2 in l2s]
    grid = minimize_lbfgs_glm_grid_streaming(
        sobj, _x0s(3, d), l2s, max_iter=25)
    for gi, (s, g) in enumerate(zip(seq, grid)):
        assert g.iterations == s.iterations, gi
        assert g.reason == s.reason, gi
        np.testing.assert_allclose(np.asarray(g.x), np.asarray(s.x),
                                   rtol=2e-3, atol=1e-4)
    assert int(np.argmin([float(r.value) for r in grid])) == \
        int(np.argmin([float(r.value) for r in seq]))


def test_grid_tron_parity_bounds(problem):
    """Batched TRON G>1: vmapped reduction order may flip an accept
    decision at the trust-region boundary, so (unlike L-BFGS) iteration
    counts are NOT asserted — the contract is per-coefficient agreement
    within documented bounds plus identical selection."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    l2s = np.asarray([0.3, 3.0], np.float32)
    seq = [minimize_tron_streaming(
        sobj, jnp.zeros(d, jnp.float32), l2, max_iter=10) for l2 in l2s]
    grid = minimize_tron_grid_streaming(sobj, _x0s(2, d), l2s, max_iter=10)
    for gi, (s, g) in enumerate(zip(seq, grid)):
        np.testing.assert_allclose(np.asarray(g.x), np.asarray(s.x),
                                   rtol=1e-3, atol=1e-3, err_msg=str(gi))
    assert int(np.argmin([float(r.value) for r in grid])) == \
        int(np.argmin([float(r.value) for r in seq]))


# -- masked convergence edge cases ------------------------------------------


def test_all_rows_identical_lambda_converge_together(problem):
    """Degenerate masking: every row the same λ ⇒ every row converges at
    the same outer iteration with the same reason and identical
    coefficient rows (the mask never splits the batch)."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    res = minimize_lbfgs_glm_grid_streaming(
        sobj, _x0s(3, d), np.asarray([2.0, 2.0, 2.0], np.float32),
        max_iter=20)
    assert len({int(r.iterations) for r in res}) == 1
    assert len({int(r.reason) for r in res}) == 1
    assert _bits(res[0].x) == _bits(res[1].x) == _bits(res[2].x)


def test_max_iters_row_rides_along_frozen(problem):
    """A slow row hitting max_iter must not perturb rows that converged
    earlier: the tiny-λ row reports MAX_ITERATIONS while the heavy-λ
    rows converge, and each converged row equals its own sequential
    solve bit-for-... (to accumulation tolerance)."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    l2s = np.asarray([1e-4, 50.0], np.float32)
    res = minimize_lbfgs_glm_grid_streaming(
        sobj, _x0s(2, d), l2s, max_iter=4, tol=1e-9)
    assert res[0].reason == int(ConvergenceReason.MAX_ITERATIONS)
    assert res[0].iterations == 4
    # frozen ride-along: the converged/stopped rows match sequential
    for gi, l2 in enumerate(l2s):
        s = minimize_lbfgs_glm_streaming(
            sobj, jnp.zeros(d, jnp.float32), l2, max_iter=4, tol=1e-9)
        assert res[gi].iterations == s.iterations, gi
        np.testing.assert_allclose(np.asarray(res[gi].x), np.asarray(s.x),
                                   rtol=2e-3, atol=1e-4)


# -- per-λ observability under batching -------------------------------------


def test_ring_structure_batched_equals_sequential(problem):
    """Satellite regression: each λ's ConvergenceRing under batching has
    the SAME structure as its sequential solve's ring — same entry
    count, same iteration column, matching loss/grad-norm values."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    l2s = np.asarray([0.3, 3.0, 30.0], np.float32)
    seq_rings = [ConvergenceRing() for _ in l2s]
    for ring, l2 in zip(seq_rings, l2s):
        minimize_lbfgs_glm_streaming(
            sobj, jnp.zeros(d, jnp.float32), l2, max_iter=15,
            convergence_ring=ring)
    grid_rings = [ConvergenceRing() for _ in l2s]
    minimize_lbfgs_glm_grid_streaming(
        sobj, _x0s(3, d), l2s, max_iter=15, convergence_rings=grid_rings)
    for gi, (sr, gr) in enumerate(zip(seq_rings, grid_rings)):
        s, g = sr.snapshot()["tail"], gr.snapshot()["tail"]
        assert len(g) == len(s), gi
        assert [e["iteration"] for e in g] == \
            [e["iteration"] for e in s], gi
        np.testing.assert_allclose([e["value"] for e in g],
                                   [e["value"] for e in s],
                                   rtol=1e-3, err_msg=str(gi))
        # grad norms shrink to ~tol: near-zero tails are relatively
        # noisy between the vmapped and the scalar accumulation — the
        # regression target is the ring STRUCTURE plus a loose value
        # envelope, not bitwise trajectories.
        np.testing.assert_allclose([e["grad_norm"] for e in g],
                                   [e["grad_norm"] for e in s],
                                   rtol=0.5, atol=1e-2, err_msg=str(gi))


def test_poisoned_lambda_diverges_row_isolated(problem):
    """A NaN λ row must fail as ITSELF: SolverDivergedError carries the
    row's λ, grid row index, and ITS per-λ trace id (not the sweep's or
    a neighbour's), and the healthy rows' rings hold only finite
    entries up to the raise."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    l2s = np.asarray([0.5, np.nan, 2.0], np.float32)
    ctxs = [telemetry.mint("solve") for _ in l2s]
    rings = [ConvergenceRing() for _ in l2s]
    with pytest.raises(SolverDivergedError) as exc:
        minimize_lbfgs_glm_grid_streaming(
            sobj, _x0s(3, d), l2s, max_iter=10,
            trace_ctxs=ctxs, convergence_rings=rings)
    err = exc.value
    assert err.grid_row == 1
    assert np.isnan(err.lam)
    assert err.trace_id == ctxs[1].trace_id
    assert "grid row 1" in str(err)
    for gi in (0, 2):
        for entry in rings[gi].snapshot()["tail"]:
            assert np.isfinite(entry["value"]), gi
            assert np.isfinite(entry["grad_norm"]), gi


def test_grid_telemetry_pass_counter_and_active_gauge(problem):
    """training.grid.feature_passes counts every batched pass;
    training.grid.active_points ends a sweep at 0 (all rows retired)."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    telemetry.reset()
    telemetry.enable()
    try:
        counter = telemetry.counter("training.grid.feature_passes")
        gauge = telemetry.gauge("training.grid.active_points")
        minimize_lbfgs_glm_grid_streaming(
            sobj, _x0s(2, X.shape[1]), np.asarray([0.5, 5.0], np.float32),
            max_iter=6)
        assert counter.value > 0
        assert gauge.calls > 0  # was live during the sweep ...
        assert gauge.value == 0  # ... and retired every row at the end
    finally:
        telemetry.disable()
        telemetry.reset()


def test_grid_gauge_federation_policy():
    """Fleet merge: active grid points SUM across processes (each
    process sweeps its own grid slice)."""
    from photon_ml_tpu.telemetry.federation import gauge_merge_policy

    assert gauge_merge_policy("training.grid.active_points") == "sum"


# -- feature-pass economics -------------------------------------------------


def test_feature_passes_independent_of_grid_width(problem):
    """THE perf claim: a sweep's streamed epochs depend on the iteration
    count, not on G — G=2 and G=4 grids with the same schedule replay
    the cache the same number of times (sequential would pay ~G×)."""
    X, y, off, w = problem
    d = X.shape[1]
    epochs = {}
    for G in (2, 4):
        sobj = _sharded(X, y, off, w, budget=40_000)
        base = sobj.cache.stats()["epochs"]
        l2s = np.geomspace(0.5, 50.0, G).astype(np.float32)
        minimize_lbfgs_glm_grid_streaming(
            sobj, _x0s(G, d), l2s, max_iter=8, tol=0.0)
        epochs[G] = sobj.cache.stats()["epochs"] - base
    assert epochs[2] == epochs[4] > 0


def test_grid_compile_counts_bounded_and_flat_across_lambdas(problem):
    """TracingGuard budgets hold for the grid kernels, and a second
    sweep with DIFFERENT λ values (same G) compiles nothing new — λ is
    a traced argument, exactly like the scalar streamed solvers."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    d = X.shape[1]
    minimize_lbfgs_glm_grid_streaming(
        sobj, _x0s(2, d), np.asarray([0.5, 5.0], np.float32), max_iter=6)
    sobj.assert_trace_budget()
    counts = dict(sobj.guard.counts())
    assert any(k.startswith("sharded:grid_") and v > 0
               for k, v in counts.items())
    minimize_lbfgs_glm_grid_streaming(
        sobj, _x0s(2, d), np.asarray([0.01, 900.0], np.float32),
        max_iter=6)
    assert sobj.guard.counts() == counts
    sobj.assert_trace_budget()


def test_sequential_sweep_never_compiles_grid_kernels(problem):
    """Grid kits build lazily: a sharded objective used only by scalar
    streamed solves must carry zero grid-kernel traces (and no grid
    entries in its declared budgets)."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w)
    minimize_lbfgs_glm_streaming(
        sobj, jnp.zeros(X.shape[1], jnp.float32), np.float32(1.0),
        max_iter=5)
    assert not any(k.startswith("sharded:grid_")
                   for k in sobj.guard.counts())
    assert not any(k.startswith("sharded:grid_")
                   for k in sobj.trace_budgets())


# -- coordinate-level entry point -------------------------------------------


def _cfg(l2, optimizer="LBFGS", max_iterations=12):
    return GLMOptimizationConfiguration.parse(
        f"{max_iterations},1e-7,{l2},1.0,{optimizer},L2")


def test_solve_fixed_effect_grid_matches_sequential_coordinate(problem):
    """coordinate-level sweep: solve_fixed_effect_grid returns the same
    (model, result) rows G sequential coordinate.solve calls produce
    (selection-grade agreement), slicing per-row margins out of the
    batched [G, rows] holder."""
    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 96, off, w), "g", hbm_budget_bytes=None)
    configs = [_cfg(0.5), _cfg(5.0)]
    holder = []
    coord = StreamingFixedEffectCoordinate(
        name="fixed", cache=cache, feature_shard_id="g",
        task_type=TaskType.LOGISTIC_REGRESSION, config=configs[0])
    pairs = solve_fixed_effect_grid(coord, configs, margins_out=holder)
    assert len(pairs) == 2
    shared = coord.sharded_objective
    for gi, cfg in enumerate(configs):
        seq_coord = StreamingFixedEffectCoordinate(
            name="fixed", cache=cache, feature_shard_id="g",
            task_type=TaskType.LOGISTIC_REGRESSION, config=cfg,
            sharded_objective=shared)
        seq_holder = []
        _, seq_res = seq_coord.solve(None, margins_out=seq_holder)
        model, res = pairs[gi]
        np.testing.assert_allclose(
            np.asarray(model.glm.coefficients.means),
            np.asarray(seq_res.x), rtol=2e-3, atol=1e-4)
        assert res.iterations == seq_res.iterations
        row = shared.grid_row_margins(holder, gi)
        for zr, zs in zip(row, seq_holder):
            np.testing.assert_allclose(np.asarray(zr), np.asarray(zs),
                                       rtol=2e-3, atol=1e-3)


def test_grid_batchable_rejections():
    ok, why = grid_batchable([])
    assert not ok and "empty" in why
    assert grid_batchable([_cfg(0.5), _cfg(5.0)])[0]
    # heterogeneous optimizer
    ok, why = grid_batchable([_cfg(0.5), _cfg(5.0, optimizer="TRON")])
    assert not ok and "optimizer" in why
    # heterogeneous schedule
    ok, why = grid_batchable([_cfg(0.5), _cfg(5.0, max_iterations=30)])
    assert not ok and "max_iterations" in why
    # L1 grid points
    l1_cfg = GLMOptimizationConfiguration.parse(
        "12,1e-7,0.5,1.0,LBFGS,L1")
    ok, why = grid_batchable([l1_cfg])
    assert not ok and "L1" in why


# -- deterministic tie-break ------------------------------------------------


def _fake_result(objective):
    return CoordinateDescentResult(
        model=object(), objective_history=[objective],
        validation_history=[], best_model=None, best_metric=None,
        trackers={}, timings={})


def test_select_best_result_exact_tie_goes_to_smallest_lambda():
    """Documented contract: an EXACT objective tie selects the smallest
    λ, whatever order the sweep enumerated the grid in — batched and
    sequential sweeps can never disagree on the selected model."""
    lo = ({"fixed": _cfg(0.5)}, _fake_result(1.25))
    hi = ({"fixed": _cfg(5.0)}, _fake_result(1.25))
    for order in ([lo, hi], [hi, lo]):
        configs, _ = select_best_result(order, [])
        assert configs["fixed"].regularization_weight == 0.5
    # non-tie still picks the lower objective regardless of λ
    better_hi = ({"fixed": _cfg(5.0)}, _fake_result(1.0))
    configs, _ = select_best_result([lo, better_hi], [])
    assert configs["fixed"].regularization_weight == 5.0


def test_select_best_result_validation_tie_break():
    class Auc:
        name = "AUC"

        @staticmethod
        def better_than(a, b):
            return a > b

    def with_val(cfg_l2, metric):
        res = _fake_result(1.0)
        res.validation_history.append({"AUC": metric})
        return ({"fixed": _cfg(cfg_l2)}, res)

    tie_small, tie_big = with_val(0.5, 0.8), with_val(5.0, 0.8)
    for order in ([tie_small, tie_big], [tie_big, tie_small]):
        configs, _ = select_best_result(order, [Auc()])
        assert configs["fixed"].regularization_weight == 0.5
    # a strictly better metric still wins over a smaller λ
    configs, _ = select_best_result(
        [with_val(0.5, 0.7), with_val(5.0, 0.9)], [Auc()])
    assert configs["fixed"].regularization_weight == 5.0
