"""Native C Avro decoder (photon_ml_tpu/native/_avro_native.c): bit-exact
equivalence with the pure-python read_datum across schema shapes, plus
graceful fallback."""

import numpy as np
import pytest

from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import (
    Schema,
    compile_schema_program,
    read_container,
    write_container,
)
from photon_ml_tpu.native import load_avro_native

native = load_avro_native()
pytestmark = pytest.mark.skipif(
    native is None, reason="no C compiler available for the native decoder")


def _roundtrip_both(tmp_path, schema, records):
    """Write once; read with the native path and the forced-python path."""
    p = tmp_path / "data.avro"
    write_container(p, schema, records)
    got_native = list(read_container(p))

    import photon_ml_tpu.native as nat

    saved = (nat._loaded, nat._module)
    nat._loaded, nat._module = True, None
    try:
        got_python = list(read_container(p))
    finally:
        nat._loaded, nat._module = saved
    return got_native, got_python


def test_training_examples_equal(tmp_path, rng):
    records = []
    for i in range(500):
        records.append({
            "uid": f"u{i}" if i % 3 else None,
            "label": float(rng.normal()),
            "features": [
                {"name": f"f{j}", "term": "t" if j % 2 else None,
                 "value": float(rng.normal())}
                for j in range(int(rng.integers(0, 8)))],
            "weight": float(rng.random()) if i % 2 else None,
            "offset": None,
            "metadataMap": {"userId": f"user{i % 7}", "k": "v"} if i % 4
            else None,
        })
    a, b = _roundtrip_both(tmp_path, schemas.TRAINING_EXAMPLE, records)
    assert a == b == records


def test_exotic_schema_equal(tmp_path):
    schema = {
        "type": "record", "name": "Exotic", "fields": [
            {"name": "e", "type": {"type": "enum", "name": "Color",
                                   "symbols": ["RED", "GREEN", "BLUE"]}},
            {"name": "fx", "type": {"type": "fixed", "name": "F8",
                                    "size": 8}},
            {"name": "b", "type": "bytes"},
            {"name": "flag", "type": "boolean"},
            {"name": "i", "type": "int"},
            {"name": "l", "type": "long"},
            {"name": "f", "type": "float"},
            {"name": "nested", "type": {"type": "array", "items": {
                "type": "map", "values": ["null", "double", "string"]}}},
        ]}
    records = [
        {"e": "GREEN", "fx": b"12345678", "b": b"\x00\xff", "flag": True,
         "i": -2**31, "l": 2**62 - 1, "f": 1.5,
         "nested": [{"a": None, "b": 3.25}, {}, {"s": "ünicøde"}]},
        {"e": "RED", "fx": b"\x00" * 8, "b": b"", "flag": False,
         "i": 0, "l": -2**62, "f": -0.0, "nested": []},
    ]
    a, b = _roundtrip_both(tmp_path, schema, records)
    assert a == b == records


def test_all_bundled_schemas_compile():
    for name in ("NAME_TERM_VALUE", "TRAINING_EXAMPLE",
                 "BAYESIAN_LINEAR_MODEL", "LATENT_FACTOR", "SCORING_RESULT",
                 "FEATURE_SUMMARIZATION_RESULT"):
        schema = getattr(schemas, name)
        prog = compile_schema_program(Schema(schema).root)
        assert prog is not None, name


def test_truncated_block_raises():
    prog = compile_schema_program(Schema(schemas.NAME_TERM_VALUE).root)
    with pytest.raises(ValueError):
        native.decode_block(b"\x02", 1, prog.prog, prog.root, prog.strings)


def test_trailing_bytes_raise():
    prog = compile_schema_program(Schema("long").root)
    with pytest.raises(ValueError, match="trailing"):
        native.decode_block(b"\x02\x02", 1, prog.prog, prog.root,
                            prog.strings)
    assert native.decode_block(b"\x02\x04", 2, prog.prog, prog.root,
                               prog.strings) == [1, 2]


# The reference's own wire layout: term is a PLAIN string, not a
# [null, string] union, and metadataMap/weight/offset come after features
# (photon-avro-schemas/src/main/avro/TrainingExampleAvro.avsc,
# FeatureAvro.avsc).
REFERENCE_TRAINING_EXAMPLE = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "FeatureAvro", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string"},
                {"name": "value", "type": "double"},
            ]}}},
        {"name": "metadataMap",
         "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}


def _reference_records(n=50):
    return [
        {"uid": f"u{i}", "label": float(i % 2),
         "features": [{"name": "age", "term": "", "value": 1.0 + i},
                      {"name": "f", "term": "t2", "value": -0.5 * i}],
         "metadataMap": {"userId": f"user{i % 3}"},
         "weight": 1.0 + 0.5 * (i % 2), "offset": 0.25 * i}
        for i in range(n)]


def test_reference_layout_plain_string_term(tmp_path):
    """Plain-string terms (the reference layout) must be consumed by the
    native fast path and produce the same matrix as the python path."""
    from photon_ml_tpu.data.avro_reader import (
        build_index_map, read_labeled_points)
    from photon_ml_tpu.data.fast_ingest import fast_ingest

    p = tmp_path / "ref.avro"
    write_container(p, REFERENCE_TRAINING_EXAMPLE, _reference_records())

    imap = build_index_map(p)
    fast = fast_ingest([p], {"m": imap}, {"m": imap.intercept_index},
                       id_types=["userId"])
    assert fast is not None, "native fast path rejected the reference layout"

    mat_n, y_n, off_n, w_n, uids_n, imap_n = read_labeled_points(p)

    import photon_ml_tpu.native as nat

    saved = (nat._loaded, nat._module)
    nat._loaded, nat._module = True, None
    try:
        mat_p, y_p, off_p, w_p, uids_p, imap_p = read_labeled_points(p)
    finally:
        nat._loaded, nat._module = saved

    assert uids_n == uids_p
    np.testing.assert_array_equal(y_n, y_p)
    np.testing.assert_array_equal(off_n, off_p)
    np.testing.assert_array_equal(w_n, w_p)
    np.testing.assert_array_equal(mat_n.toarray(), mat_p.toarray())
    assert fast.ids["userId"].tolist() == [
        r["metadataMap"]["userId"] for r in _reference_records()]


def test_mixed_optional_layouts_across_files(tmp_path):
    """One file with weight/offset fields, one without: rows must stay
    aligned (absent fields default to weight=1, offset=0 per file)."""
    from photon_ml_tpu.data.avro_reader import build_index_map
    from photon_ml_tpu.data.fast_ingest import fast_ingest

    bare_schema = {
        "type": "record", "name": "TrainingExampleAvro", "fields": [
            {"name": "label", "type": "double"},
            {"name": "features", "type": {"type": "array", "items": {
                "type": "record", "name": "FeatureAvro", "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "value", "type": "double"},
                ]}}},
        ]}
    rich = tmp_path / "rich.avro"
    bare = tmp_path / "bare.avro"
    write_container(rich, REFERENCE_TRAINING_EXAMPLE, _reference_records(8))
    write_container(bare, bare_schema, [
        {"label": 10.0 + i,
         "features": [{"name": "age", "value": 2.0}]}
        for i in range(3)])

    imap = build_index_map(rich)
    fast = fast_ingest([rich, bare], {"m": imap},
                       {"m": imap.intercept_index})
    assert fast is not None
    assert len(fast.labels) == 11
    np.testing.assert_array_equal(fast.labels[8:], [10.0, 11.0, 12.0])
    # File-local defaults — no cross-file misalignment.
    np.testing.assert_array_equal(
        fast.offsets[:8], [0.25 * i for i in range(8)])
    np.testing.assert_array_equal(fast.offsets[8:], 0.0)
    np.testing.assert_array_equal(
        fast.weights[:8], [1.0 + 0.5 * (i % 2) for i in range(8)])
    np.testing.assert_array_equal(fast.weights[8:], 1.0)


def test_duplicate_metadata_key_keeps_last(tmp_path):
    """A doubly-present map key (legal on the wire) must not shift id
    alignment; last occurrence wins, matching python dict semantics."""
    import io

    from photon_ml_tpu.data.fast_ingest import build_training_layout
    from photon_ml_tpu.io.avro_codec import Schema, _write_long

    schema = {
        "type": "record", "name": "T", "fields": [
            {"name": "label", "type": "double"},
            {"name": "features", "type": {"type": "array", "items": {
                "type": "record", "name": "F", "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "value", "type": "double"},
                ]}}},
            {"name": "metadataMap",
             "type": ["null", {"type": "map", "values": "string"}]},
        ]}
    layout = build_training_layout(Schema(schema).root)
    assert layout is not None

    def wstr(buf, s):
        b = s.encode()
        _write_long(buf, len(b))
        buf.write(b)

    buf = io.BytesIO()
    buf.write(np.float64(1.0).tobytes())      # label
    _write_long(buf, 0)                        # features: empty array
    _write_long(buf, 1)                        # metadataMap: map branch
    _write_long(buf, 2)                        # one block, two entries
    wstr(buf, "userId"); wstr(buf, "first")
    wstr(buf, "userId"); wstr(buf, "second")
    _write_long(buf, 0)                        # end of map blocks

    (lb, ob, wb, us, shard_out, ids_out) = native.decode_training_block(
        buf.getvalue(), 1, layout.prog, layout.layout,
        ({},), (-1,), ("userId",), "\x01", None)
    assert list(ids_out[0]) == ["second"]
    assert np.frombuffer(lb, np.float64).tolist() == [1.0]


def test_varint_extremes():
    import io

    from photon_ml_tpu.io.avro_codec import _write_long

    vals = [0, 1, -1, 63, -64, 2**63 - 1, -2**63]
    buf = io.BytesIO()
    for v in vals:
        _write_long(buf, v)
    prog = compile_schema_program(Schema("long").root)
    out = native.decode_block(buf.getvalue(), len(vals), prog.prog,
                              prog.root, prog.strings)
    assert out == vals
