"""Native C Avro decoder (photon_ml_tpu/native/_avro_native.c): bit-exact
equivalence with the pure-python read_datum across schema shapes, plus
graceful fallback."""

import numpy as np
import pytest

from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import (
    Schema,
    compile_schema_program,
    read_container,
    write_container,
)
from photon_ml_tpu.native import load_avro_native

native = load_avro_native()
pytestmark = pytest.mark.skipif(
    native is None, reason="no C compiler available for the native decoder")


def _roundtrip_both(tmp_path, schema, records):
    """Write once; read with the native path and the forced-python path."""
    p = tmp_path / "data.avro"
    write_container(p, schema, records)
    got_native = list(read_container(p))

    import photon_ml_tpu.native as nat

    saved = (nat._loaded, nat._module)
    nat._loaded, nat._module = True, None
    try:
        got_python = list(read_container(p))
    finally:
        nat._loaded, nat._module = saved
    return got_native, got_python


def test_training_examples_equal(tmp_path, rng):
    records = []
    for i in range(500):
        records.append({
            "uid": f"u{i}" if i % 3 else None,
            "label": float(rng.normal()),
            "features": [
                {"name": f"f{j}", "term": "t" if j % 2 else None,
                 "value": float(rng.normal())}
                for j in range(int(rng.integers(0, 8)))],
            "weight": float(rng.random()) if i % 2 else None,
            "offset": None,
            "metadataMap": {"userId": f"user{i % 7}", "k": "v"} if i % 4
            else None,
        })
    a, b = _roundtrip_both(tmp_path, schemas.TRAINING_EXAMPLE, records)
    assert a == b == records


def test_exotic_schema_equal(tmp_path):
    schema = {
        "type": "record", "name": "Exotic", "fields": [
            {"name": "e", "type": {"type": "enum", "name": "Color",
                                   "symbols": ["RED", "GREEN", "BLUE"]}},
            {"name": "fx", "type": {"type": "fixed", "name": "F8",
                                    "size": 8}},
            {"name": "b", "type": "bytes"},
            {"name": "flag", "type": "boolean"},
            {"name": "i", "type": "int"},
            {"name": "l", "type": "long"},
            {"name": "f", "type": "float"},
            {"name": "nested", "type": {"type": "array", "items": {
                "type": "map", "values": ["null", "double", "string"]}}},
        ]}
    records = [
        {"e": "GREEN", "fx": b"12345678", "b": b"\x00\xff", "flag": True,
         "i": -2**31, "l": 2**62 - 1, "f": 1.5,
         "nested": [{"a": None, "b": 3.25}, {}, {"s": "ünicøde"}]},
        {"e": "RED", "fx": b"\x00" * 8, "b": b"", "flag": False,
         "i": 0, "l": -2**62, "f": -0.0, "nested": []},
    ]
    a, b = _roundtrip_both(tmp_path, schema, records)
    assert a == b == records


def test_all_bundled_schemas_compile():
    for name in ("NAME_TERM_VALUE", "TRAINING_EXAMPLE",
                 "BAYESIAN_LINEAR_MODEL", "LATENT_FACTOR", "SCORING_RESULT",
                 "FEATURE_SUMMARIZATION_RESULT"):
        schema = getattr(schemas, name)
        prog = compile_schema_program(Schema(schema).root)
        assert prog is not None, name


def test_truncated_block_raises():
    prog = compile_schema_program(Schema(schemas.NAME_TERM_VALUE).root)
    with pytest.raises(ValueError):
        native.decode_block(b"\x02", 1, prog.prog, prog.root, prog.strings)


def test_trailing_bytes_raise():
    prog = compile_schema_program(Schema("long").root)
    with pytest.raises(ValueError, match="trailing"):
        native.decode_block(b"\x02\x02", 1, prog.prog, prog.root,
                            prog.strings)
    assert native.decode_block(b"\x02\x04", 2, prog.prog, prog.root,
                               prog.strings) == [1, 2]


def test_varint_extremes():
    import io

    from photon_ml_tpu.io.avro_codec import _write_long

    vals = [0, 1, -1, 63, -64, 2**63 - 1, -2**63]
    buf = io.BytesIO()
    for v in vals:
        _write_long(buf, v)
    prog = compile_schema_program(Schema("long").root)
    out = native.decode_block(buf.getvalue(), len(vals), prog.prog,
                              prog.root, prog.strings)
    assert out == vals
