"""Damped exact-Newton solver tests (the small-d TRON fast path,
optimization/newton.py). Same test pattern as the other optimizers:
known convex functions + scipy cross-checks + vmap batch equivalence.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

# Exact Newton is the explicit-use CPU/f64 tool (solver.py routes TPU
# solves to CG/quasi-Newton); its curvature solves stall around gnorm
# ~1e-2 in f32, so the module is f64-only.
pytestmark = pytest.mark.needs_f64
import scipy.optimize

from photon_ml_tpu.ops import DenseFeatures, GLMObjective, LogisticLoss
from photon_ml_tpu.ops.glm_objective import make_batch
from photon_ml_tpu.optimization import (
    ConvergenceReason,
    minimize_newton,
    minimize_tron,
)

CENTER = np.asarray([1.0, -2.0, 3.0, 0.5, -0.25])
SCALES = jnp.asarray([1.0, 2.0, 0.5, 4.0, 1.5])


def quad(x, scale):
    d = x - jnp.asarray(CENTER, x.dtype)
    return jnp.sum(scale * d * d)


def test_quadratic_one_newton_step():
    res = minimize_newton(quad, jnp.zeros(5), args=(SCALES,), tol=1e-12)
    np.testing.assert_allclose(np.asarray(res.x), CENTER, atol=1e-8)
    # Quadratic: (nearly) one damped-Newton step.
    assert int(res.iterations) <= 3
    assert res.reason_enum() in (
        ConvergenceReason.GRADIENT_CONVERGED,
        ConvergenceReason.FUNCTION_VALUES_CONVERGED,
    )


def _logistic_problem(n=300, d=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    return x, y


def test_matches_scipy_on_logistic():
    x, y = _logistic_problem()
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    l2 = 0.5

    res = minimize_newton(obj.value, jnp.zeros(6), args=(batch, l2),
                          tol=1e-10, max_iter=50)

    def f_np(w):
        return float(obj.value(jnp.asarray(w), batch, l2))

    ref = scipy.optimize.minimize(f_np, np.zeros(6), method="Nelder-Mead",
                                  options={"xatol": 1e-8, "fatol": 1e-12,
                                           "maxiter": 5000})
    assert float(res.value) <= ref.fun + 1e-6


def test_matches_tron_solution():
    x, y = _logistic_problem(seed=3)
    obj = GLMObjective(LogisticLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    rn = minimize_newton(obj.value, jnp.zeros(6), args=(batch, 0.3),
                         tol=1e-10, max_iter=50)
    rt = minimize_tron(obj.value, jnp.zeros(6), args=(batch, 0.3),
                       tol=1e-10, max_iter=50)
    np.testing.assert_allclose(np.asarray(rn.x), np.asarray(rt.x), atol=1e-5)


def test_box_constraints_projection():
    lb = jnp.asarray([0.0, -1.0, 0.0, 0.0, -1.0])
    ub = jnp.asarray([0.5, 0.0, 10.0, 0.1, 0.0])
    res = minimize_newton(quad, jnp.zeros(5), args=(SCALES,), tol=1e-12,
                          lower_bounds=lb, upper_bounds=ub)
    expected = np.clip(CENTER, np.asarray(lb), np.asarray(ub))
    np.testing.assert_allclose(np.asarray(res.x), expected, atol=1e-6)


def test_vmap_batch_matches_individual():
    """The mode that matters: thousands of entity solves as one batched
    kernel must agree with per-problem solves."""
    rng = np.random.default_rng(7)
    E, n, d = 5, 40, 4
    xs = rng.normal(size=(E, n, d))
    ws = rng.normal(size=(E, d))
    ys = (rng.random((E, n)) < 1 / (1 + np.exp(
        -np.einsum("end,ed->en", xs, ws)))).astype(float)
    obj = GLMObjective(LogisticLoss)

    def fit(x, y):
        batch = make_batch(DenseFeatures(x), y)
        return minimize_newton(obj.value, jnp.zeros(d, x.dtype),
                               args=(batch, 0.5), tol=1e-10)

    batched = jax.vmap(fit)(jnp.asarray(xs), jnp.asarray(ys))
    for e in range(E):
        single = fit(jnp.asarray(xs[e]), jnp.asarray(ys[e]))
        np.testing.assert_allclose(np.asarray(batched.x[e]),
                                   np.asarray(single.x), atol=1e-6)


def test_coef_history_tracking():
    res = minimize_newton(quad, jnp.zeros(5), args=(SCALES,), tol=1e-12,
                          track_coefficients=True)
    hist = np.asarray(res.coef_history)
    iters = int(res.iterations)
    np.testing.assert_allclose(hist[iters], np.asarray(res.x), atol=0)
    assert np.all(np.isnan(hist[iters + 1:]))


def test_poisson_newton():
    rng = np.random.default_rng(2)
    n, d = 200, 5
    x = rng.normal(0, 0.4, size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(0, 0.5, size=d)
    y = rng.poisson(np.exp(x @ w)).astype(float)
    from photon_ml_tpu.ops.losses import PoissonLoss
    obj = GLMObjective(PoissonLoss)
    batch = make_batch(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    res = minimize_newton(obj.value, jnp.zeros(d), args=(batch, 0.1),
                          tol=1e-10, max_iter=50)
    g = jax.grad(obj.value)(res.x, batch, 0.1)
    assert float(jnp.linalg.norm(g)) < 1e-4
