"""End-to-end CLI driver tests — the analog of the reference's DriverTest
(1034 LoC) and cli/game/*/DriverTest integration suites, on generated Avro
fixtures instead of checked-in ones.
"""

import json

import numpy as np
import pytest

from photon_ml_tpu.cli import (  # noqa: F401  (import check)
    feature_indexing,
    game_scoring_driver,
    game_training_driver,
    glm_driver,
)
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import read_container, write_container
from photon_ml_tpu.utils.events import (
    EventListener,
    PhotonOptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)


def _write_glm_avro(path, rng, n=200, d=5, poisson=False, w=None):
    if w is None:
        w = rng.normal(0, 1, d + 1)
    records = []
    for i in range(n):
        idx = rng.choice(d, size=rng.integers(1, d + 1), replace=False)
        vals = rng.normal(0, 1, len(idx))
        z = float(vals @ w[idx] + w[-1])
        if poisson:
            label = float(rng.poisson(np.exp(np.clip(z, -5, 3))))
        else:
            label = float(rng.random() < 1 / (1 + np.exp(-z)))
        records.append({
            "uid": f"u{i}", "label": label,
            "features": [{"name": f"f{j}", "term": None, "value": float(v)}
                         for j, v in zip(idx, vals)],
            "weight": None, "offset": None, "metadataMap": None})
    path.mkdir(parents=True, exist_ok=True)
    write_container(path / "part-00000.avro", schemas.TRAINING_EXAMPLE,
                    records)


def _write_game_avro(path, rng, n=300, n_users=10, params=None):
    if params is None:
        user_bias = rng.normal(0, 1.5, n_users)
        w = rng.normal(0, 1, 3)
    else:
        user_bias, w = params
    records = []
    for i in range(n):
        u = int(rng.integers(0, n_users))
        x = rng.normal(0, 1, 3)
        z = float(x @ w + user_bias[u])
        records.append({
            "uid": f"r{i}", "label": float(rng.random() < 1 / (1 + np.exp(-z))),
            "features": [{"name": f"x{j}", "term": None, "value": float(v)}
                         for j, v in enumerate(x)],
            "weight": None, "offset": None,
            "metadataMap": {"userId": f"user{u}"}})
    path.mkdir(parents=True, exist_ok=True)
    write_container(path / "part-00000.avro", schemas.TRAINING_EXAMPLE,
                    records)


def test_glm_driver_avro_end_to_end(tmp_path, rng):
    train = tmp_path / "train"
    valid = tmp_path / "valid"
    w_true = rng.normal(0, 1, 6)
    _write_glm_avro(train, rng, n=300, w=w_true)
    _write_glm_avro(valid, rng, n=100, w=w_true)
    out = tmp_path / "out"
    summary = glm_driver.run([
        "--training-data-directory", str(train),
        "--validating-data-directory", str(valid),
        "--output-directory", str(out),
        "--task", "LOGISTIC_REGRESSION",
        "--regularization-weights", "10,1,0.1",
        "--max-num-iterations", "60",
        "--dtype", "float64",
    ])
    assert summary["stages"] == ["INIT", "PREPROCESSED", "TRAINED",
                                 "VALIDATED"]
    assert summary["bestLambda"] in (10.0, 1.0, 0.1)
    assert (out / "best-model" / "model.txt").exists()
    assert (out / "best-model" / "model.avro").exists()
    assert (out / "log-message.txt").exists()
    # validation-metrics.json shape: {"metrics": {λ: {...}},
    # "metricMetadata": {name: {...}}}
    vm = json.loads((out / "validation-metrics.json").read_text())
    assert set(vm) == {"metrics", "metricMetadata"}
    assert set(vm["metrics"]) == {"10.0", "1.0", "0.1"}
    assert vm["metrics"][str(summary["bestLambda"])]["AUC"] > 0.6
    assert vm["metricMetadata"]["AUC"]["higherIsBetter"] is True
    assert vm["metricMetadata"]["AUC"]["range"] == [0.0, 1.0]
    # text model format: 4 tab-separated columns
    line = (out / "best-model" / "model.txt").read_text().splitlines()[0]
    assert len(line.split("\t")) == 4
    # AUC should beat random on in-distribution validation data
    metrics = summary["validationMetrics"][str(summary["bestLambda"])]
    assert metrics["AUC"] > 0.6
    # all three lambdas produced models
    assert len(list((out / "all-models").iterdir())) == 3


def test_glm_driver_libsvm_tron_poisson(tmp_path, rng):
    # LIBSVM ingest + TRON + linear regression path
    f = tmp_path / "train" / "data.libsvm"
    f.parent.mkdir()
    lines = []
    w = rng.normal(0, 1, 4)
    for _ in range(150):
        x = rng.normal(0, 1, 4)
        y = x @ w + rng.normal(0, 0.1)
        feats = " ".join(f"{j+1}:{x[j]:.5f}" for j in range(4))
        lines.append(f"{y:.5f} {feats}")
    f.write_text("\n".join(lines) + "\n")
    out = tmp_path / "out"
    summary = glm_driver.run([
        "--training-data-directory", str(f.parent),
        "--output-directory", str(out),
        "--task", "LINEAR_REGRESSION",
        "--format", "LIBSVM",
        "--optimizer", "TRON",
        "--regularization-weights", "0.01",
        "--dtype", "float64",
    ])
    conv = summary["convergence"]["0.01"]
    assert conv["finalObjective"] < 10.0  # near-noise-floor fit


def test_glm_driver_normalization_and_constraints(tmp_path, rng):
    train = tmp_path / "train"
    _write_glm_avro(train, rng, n=200)
    out = tmp_path / "out"
    constraints = json.dumps([
        {"name": "*", "term": "*", "lowerBound": -0.5, "upperBound": 0.5}])
    summary = glm_driver.run([
        "--training-data-directory", str(train),
        "--output-directory", str(out),
        "--task", "LOGISTIC_REGRESSION",
        "--normalization-type", "STANDARDIZATION",
        "--coefficient-box-constraints", constraints,
        "--regularization-weights", "1",
        "--dtype", "float64",
    ])
    assert "TRAINED" in summary["stages"]


def test_game_pipeline_train_then_score(tmp_path, rng):
    train = tmp_path / "train"
    valid = tmp_path / "valid"
    params = (rng.normal(0, 1.5, 10), rng.normal(0, 1, 3))
    _write_game_avro(train, rng, n=400, params=params)
    _write_game_avro(valid, rng, n=150, params=params)
    out = tmp_path / "game-out"

    summary = game_training_driver.run([
        "--train-input-dirs", str(train),
        "--validate-input-dirs", str(valid),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:30,1e-7,1.0,1.0,LBFGS,L2",
        "--random-effect-data-configurations",
        "perUser:userId,global,4,-1,-1,-1",
        "--random-effect-optimization-configurations",
        "perUser:20,1e-7,1.0,1.0,LBFGS,L2",
        "--updating-sequence", "fixed,perUser",
        "--num-iterations", "2",
        "--evaluators", "AUC,LOGISTIC_LOSS",
    ])
    assert summary["numCombos"] == 1
    assert len(summary["validationHistory"]) == 2
    assert summary["validationHistory"][-1]["AUC"] > 0.6
    assert (out / "best" / "model-metadata.json").exists()
    assert (out / "best" / "feature-indexes" / "global.json").exists()

    score_out = tmp_path / "score-out"
    score_summary = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(out / "best"),
        "--output-dir", str(score_out),
        "--evaluators", "AUC",
    ])
    assert score_summary["numRows"] == 150
    # Scoring the same validation data reproduces the training-time AUC.
    np.testing.assert_allclose(
        score_summary["metrics"]["AUC"],
        summary["validationHistory"][-1]["AUC"], atol=1e-9)
    scored = list(read_container(score_out / "scores" / "part-00000.avro"))
    assert len(scored) == 150
    assert {"uid", "predictionScore", "label"} <= set(scored[0])


def _train_small_game(tmp_path, rng, n_train=300, n_valid=140):
    train = tmp_path / "train"
    valid = tmp_path / "valid"
    params = (rng.normal(0, 1.5, 10), rng.normal(0, 1, 3))
    _write_game_avro(train, rng, n=n_train, params=params)
    _write_game_avro(valid, rng, n=n_valid, params=params)
    out = tmp_path / "game-out"
    game_training_driver.run([
        "--train-input-dirs", str(train),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:20,1e-7,1.0,1.0,LBFGS,L2",
        "--random-effect-data-configurations",
        "perUser:userId,global,4,-1,-1,-1",
        "--random-effect-optimization-configurations",
        "perUser:15,1e-7,1.0,1.0,LBFGS,L2",
        "--updating-sequence", "fixed,perUser",
        "--num-iterations", "1",
    ])
    return out / "best", valid


def test_game_scoring_stream_matches_batch(tmp_path, rng):
    """--stream --batch-rows N (bounded-memory serving-engine path) must
    reproduce the one-shot scoring run: same Avro score records, same
    metrics — padded batch boundaries never leak into output."""
    model_dir, valid = _train_small_game(tmp_path, rng)

    batch_out = tmp_path / "score-batch"
    batch = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(batch_out),
        "--evaluators", "AUC,LOGISTIC_LOSS",
    ])
    stream_out = tmp_path / "score-stream"
    stream = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(stream_out),
        "--evaluators", "AUC,LOGISTIC_LOSS",
        "--stream", "--batch-rows", "33",  # uneven: forces partial batch
    ])
    assert stream["numRows"] == batch["numRows"] == 140
    assert batch["scoringPath"] == "device"  # snapshot models device-score
    assert stream["scoringPath"] == "streaming-engine"
    assert stream["numBatches"] == 5  # ceil(140 / 33)
    for name, v in batch["metrics"].items():
        np.testing.assert_allclose(stream["metrics"][name], v, atol=1e-9)
    recs_b = list(read_container(batch_out / "scores" / "part-00000.avro"))
    recs_s = list(read_container(stream_out / "scores" / "part-00000.avro"))
    assert [r["uid"] for r in recs_s] == [r["uid"] for r in recs_b]
    np.testing.assert_allclose(
        [r["predictionScore"] for r in recs_s],
        [r["predictionScore"] for r in recs_b], rtol=1e-9, atol=1e-12)
    # engine telemetry rode along: compile cache stayed small
    assert stream["engine"]["compilations"] <= \
        stream["engine"]["dispatches"]
    # feeder telemetry: decode path + bounded residency (prefetch default 2)
    feeder = stream["feeder"]
    assert feeder["decode_path"] in ("native", "python")
    assert feeder["batches"] == 5
    assert feeder["rows"] == 140
    assert feeder["peak_resident_batches"] <= feeder["prefetch_depth"] + 2

    # The forced-python feeder (no prefetch) writes the SAME bytes — the
    # decode path can never change a score.
    py_out = tmp_path / "score-stream-py"
    py = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(py_out),
        "--stream", "--batch-rows", "33",
        "--feeder", "python", "--prefetch-batches", "0",
    ])
    assert py["feeder"]["decode_path"] == "python"
    recs_p = list(read_container(py_out / "scores" / "part-00000.avro"))
    assert [(r["uid"], r["predictionScore"]) for r in recs_p] == \
        [(r["uid"], r["predictionScore"]) for r in recs_s]


def test_game_scoring_host_fallback_on_unsupported_model(
        tmp_path, rng, monkeypatch):
    """A model family the device scorer rejects — the TYPED
    UnsupportedSubModelError contract — must fall back to host numpy
    scoring, not crash the driver."""
    model_dir, valid = _train_small_game(tmp_path, rng, n_train=200,
                                         n_valid=60)
    from photon_ml_tpu.models import device_scoring
    from photon_ml_tpu.serving.kernels import UnsupportedSubModelError

    def boom(*a, **kw):
        raise UnsupportedSubModelError("synthetic: unsupported sub-model")

    monkeypatch.setattr(device_scoring, "DeviceGameScorer", boom)
    out = tmp_path / "score-fallback"
    summary = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(out),
        "--evaluators", "AUC",
    ])
    assert summary["numRows"] == 60
    assert summary["scoringPath"] == "host"
    assert (out / "scores" / "part-00000.avro").exists()


def test_game_scoring_engine_bug_surfaces(tmp_path, rng, monkeypatch):
    """Satellite regression: the host fallback is RESTRICTED to the
    documented unsupported-sub-model case — an injected bare TypeError
    out of the engine (a real bug) must surface, never silently degrade
    to host scoring."""
    model_dir, valid = _train_small_game(tmp_path, rng, n_train=200,
                                         n_valid=60)
    from photon_ml_tpu.models import device_scoring

    def boom(*a, **kw):
        raise TypeError("synthetic: engine bug, not the documented "
                        "unsupported-sub-model contract")

    monkeypatch.setattr(device_scoring, "DeviceGameScorer", boom)
    with pytest.raises(TypeError, match="engine bug"):
        game_scoring_driver.run([
            "--input-dirs", str(valid),
            "--game-model-input-dir", str(model_dir),
            "--output-dir", str(tmp_path / "score-bug"),
        ])


def test_game_scoring_serve_matches_batch(tmp_path, rng):
    """Tier-1 smoke for the async front-end CLI mode: --serve replays
    the input as concurrent coalesced requests (python feeder, so it
    runs everywhere) and must reproduce the one-shot scores exactly, in
    order, with the frontend telemetry block in metrics.json."""
    model_dir, valid = _train_small_game(tmp_path, rng)

    batch_out = tmp_path / "score-batch"
    batch = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(batch_out),
        "--evaluators", "AUC",
    ])
    serve_out = tmp_path / "score-serve"
    serve = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(serve_out),
        "--evaluators", "AUC",
        "--serve", "--request-rows", "7", "--serve-concurrency", "8",
        "--coalesce-ms", "1", "--feeder", "python",
    ])
    assert serve["num_rows"] == batch["numRows"] == 140
    assert serve["scoring_path"] == "async-frontend"
    assert serve["num_requests"] == 20  # ceil(140 / 7)
    np.testing.assert_allclose(serve["metrics"]["AUC"],
                               batch["metrics"]["AUC"], atol=1e-9)
    recs_b = list(read_container(batch_out / "scores" / "part-00000.avro"))
    recs_s = list(read_container(serve_out / "scores" / "part-00000.avro"))
    assert [r["uid"] for r in recs_s] == [r["uid"] for r in recs_b]
    np.testing.assert_allclose(
        [r["predictionScore"] for r in recs_s],
        [r["predictionScore"] for r in recs_b], rtol=1e-9, atol=1e-12)
    fe = serve["frontend"]
    assert fe["admitted"] == fe["completed"] == 20
    assert fe["rejected"] == 0
    assert fe["engines"]["default"]["requests"] == 20
    # coalescing happened: fewer device dispatches than requests
    assert fe["engines"]["default"]["dispatches"] <= 20
    # per-request latency telemetry populated (driver enables telemetry)
    assert fe["request_latency_seconds"]["count"] == 20
    assert fe["queue_wait_seconds"]["count"] == 20

    with pytest.raises(SystemExit, match="mutually exclusive"):
        game_scoring_driver.run([
            "--input-dirs", str(valid),
            "--game-model-input-dir", str(model_dir),
            "--output-dir", str(tmp_path / "score-both"),
            "--serve", "--stream",
        ])


def test_game_scoring_listen_network_front_door(tmp_path, rng):
    """--listen opens the framed network front door over the serving
    front-end: requests over BOTH framings (length-prefixed binary and
    HTTP/1.1 JSON) score byte-identically to each other, reproduce the
    one-shot batch run, and the summary carries the netserver report.
    The driver runs in a thread (it owns its own event loop); the test
    is the network client."""
    import asyncio
    import threading
    import time

    from photon_ml_tpu.data.avro_reader import iter_game_dataset_batches
    from photon_ml_tpu.data.paldb import load_feature_index_maps
    from photon_ml_tpu.serving.netserver import NetClient

    model_dir, valid = _train_small_game(tmp_path, rng, n_train=200,
                                         n_valid=40)
    batch_out = tmp_path / "score-batch"
    batch = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(batch_out),
    ])
    assert batch["numRows"] == 40
    want = [r["predictionScore"] for r in
            read_container(batch_out / "scores" / "part-00000.avro")]

    # Build the wire requests the way the driver's serve replay does:
    # featureized batches split into fixed-row requests.
    shard_maps = load_feature_index_maps(model_dir / "feature-indexes")
    requests = []
    for ds in iter_game_dataset_batches(
            [valid], id_types=["userId"], feature_shard_maps=shard_maps,
            batch_rows=64, feeder="python"):
        for a in range(0, ds.num_rows, 8):
            requests.append(ds.subset(
                np.arange(a, min(a + 8, ds.num_rows))))
    assert len(requests) == 5

    listen_out = tmp_path / "score-listen"
    result = {}

    def drive():
        result["summary"] = game_scoring_driver.run([
            "--input-dirs", str(valid),
            "--game-model-input-dir", str(model_dir),
            "--output-dir", str(listen_out),
            "--listen", "127.0.0.1:0", "--serve-seconds", "8",
            "--coalesce-ms", "1",
        ])

    t = threading.Thread(target=drive)
    t.start()
    try:
        port_file = listen_out / "net_port"
        deadline = time.time() + 60
        while not port_file.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert port_file.exists(), "--listen never published net_port"
        port = int(port_file.read_text())

        async def client():
            async with NetClient("127.0.0.1", port) as c:
                got_b = [await c.score(r) for r in requests]
            async with NetClient("127.0.0.1", port,
                                 framing="http") as c:
                got_h = [await c.score(r) for r in requests]
            return got_b, got_h

        got_b, got_h = asyncio.run(client())
    finally:
        t.join(timeout=60)
    assert not t.is_alive()

    bin_scores = np.concatenate(got_b)
    # The two framings return the SAME BYTES (JSON float repr
    # round-trips doubles exactly).
    assert bin_scores.tobytes() == np.concatenate(got_h).tobytes()
    offsets = np.concatenate([np.asarray(r.offsets) for r in requests])
    np.testing.assert_allclose(bin_scores + offsets, want,
                               rtol=1e-9, atol=1e-9)

    summary = result["summary"]
    assert summary["scoring_path"] == "netserver"
    assert summary["listen"] == "127.0.0.1:0"
    net = summary["net"]
    assert net["requests_binary"] == 5 and net["requests_http"] == 5
    assert net["responses"] == 10 and net["wire_errors"] == {}
    fe = summary["frontend"]
    assert fe["admitted"] == fe["completed"] == 10
    assert fe["rejected"] == 0


def test_game_scoring_listen_flag_validation(tmp_path):
    with pytest.raises(SystemExit, match="pass --listen"):
        game_scoring_driver.run([
            "--input-dirs", str(tmp_path),
            "--game-model-input-dir", str(tmp_path),
            "--output-dir", str(tmp_path / "out"),
            "--adaptive-admission",
        ])
    with pytest.raises(SystemExit, match="at least one --slo"):
        game_scoring_driver.run([
            "--input-dirs", str(tmp_path),
            "--game-model-input-dir", str(tmp_path),
            "--output-dir", str(tmp_path / "out"),
            "--listen", ":0", "--adaptive-admission",
        ])


def test_game_training_grid_selects_best(tmp_path, rng):
    train = tmp_path / "train"
    valid = tmp_path / "valid"
    params = (rng.normal(0, 1.5, 10), rng.normal(0, 1, 3))
    _write_game_avro(train, rng, n=250, params=params)
    _write_game_avro(valid, rng, n=100, params=params)
    out = tmp_path / "out"
    summary = game_training_driver.run([
        "--train-input-dirs", str(train),
        "--validate-input-dirs", str(valid),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:20,1e-6,10.0,1.0,LBFGS,L2|20,1e-6,0.1,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--evaluators", "AUC",
    ])
    assert summary["numCombos"] == 2
    assert "fixed" in summary["bestConfigs"]


def test_feature_indexing_job(tmp_path, rng):
    train = tmp_path / "train"
    _write_glm_avro(train, rng, n=50)
    out = feature_indexing.run([
        "--data-path", str(train),
        "--output-dir", str(tmp_path / "index"),
    ])
    from photon_ml_tpu.data.index_map import IndexMap

    imap = IndexMap.load(out)
    assert imap.intercept_index >= 0
    assert len(imap) == 6  # f0..f4 + intercept


def test_feature_indexing_job_paldb_format(tmp_path, rng):
    """--format paldb writes reference-layout partitioned stores that the
    PalDB parser (and therefore any --feature-index-dir consumer) loads
    back identically (FeatureIndexingJob.scala:145-174)."""
    train = tmp_path / "train"
    _write_glm_avro(train, rng, n=50)
    out_dir = tmp_path / "paldb-index"
    feature_indexing.run([
        "--data-path", str(train),
        "--output-dir", str(out_dir),
        "--format", "paldb",
        "--partition-num", "2",
        "--shard-name", "global",
    ])
    from photon_ml_tpu.data.paldb import load_paldb_index_map

    assert (out_dir / "paldb-partition-global-0.dat").exists()
    assert (out_dir / "paldb-partition-global-1.dat").exists()
    imap = load_paldb_index_map(out_dir, "global", 2)
    assert len(imap) == 6
    assert imap.intercept_index >= 0


def test_game_driver_rejects_unknown_sequence_entry(tmp_path, rng):
    train = tmp_path / "train"
    _write_game_avro(train, rng, n=20)
    with pytest.raises(ValueError, match="no data configuration"):
        game_training_driver.run([
            "--train-input-dirs", str(train),
            "--output-dir", str(tmp_path / "o"),
            "--task-type", "LOGISTIC_REGRESSION",
            "--fixed-effect-data-configurations", "fixed:global",
            "--fixed-effect-optimization-configurations",
            "fixed:10,1e-6,1.0,1.0,LBFGS,L2",
            "--updating-sequence", "fixed,ghost",
        ])


def test_game_training_with_factored_random_effect(tmp_path, rng):
    train = tmp_path / "train"
    valid = tmp_path / "valid"
    params = (rng.normal(0, 1.5, 10), rng.normal(0, 1, 3))
    _write_game_avro(train, rng, n=300, params=params)
    _write_game_avro(valid, rng, n=120, params=params)
    out = tmp_path / "out"
    summary = game_training_driver.run([
        "--train-input-dirs", str(train),
        "--validate-input-dirs", str(valid),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:20,1e-7,1.0,1.0,LBFGS,L2",
        "--factored-random-effect-data-configurations",
        "perUserMF:userId,global,4,-1,-1,-1",
        "--factored-random-effect-optimization-configurations",
        "perUserMF:15,1e-7,1.0,1.0,LBFGS,L2;15,1e-7,1.0,1.0,LBFGS,L2;2,2",
        "--updating-sequence", "fixed,perUserMF",
        "--num-iterations", "2",
        "--evaluators", "AUC",
    ])
    assert summary["validationHistory"][-1]["AUC"] > 0.6
    meta = json.loads((out / "best" / "model-metadata.json").read_text())
    kinds = {c["name"]: c["kind"] for c in meta["coordinates"]}
    # Factored models persist as original-space random-effect coordinates.
    assert kinds == {"fixed": "fixed", "perUserMF": "random"}

    score_out = tmp_path / "score-out"
    score_summary = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(out / "best"),
        "--output-dir", str(score_out),
        "--evaluators", "AUC",
    ])
    np.testing.assert_allclose(
        score_summary["metrics"]["AUC"],
        summary["validationHistory"][-1]["AUC"], atol=1e-6)


def test_glm_driver_selected_features_and_summarization(tmp_path, rng):
    """--selected-features-file restricts the index map to the whitelist
    (GLMSuite.scala:76-150); --summarization-output-dir writes per-feature
    FeatureSummarizationResultAvro (IOUtils.scala:270-330)."""
    train = tmp_path / "train"
    _write_glm_avro(train, rng, n=150)
    # Whitelist only f0, f1 (FeatureNameTermAvro-shaped records).
    sel = tmp_path / "selected.avro"
    write_container(sel, schemas.NAME_TERM_VALUE,
                    [{"name": "f0", "term": None, "value": 0.0},
                     {"name": "f1", "term": None, "value": 0.0}])
    out = tmp_path / "out"
    summ = tmp_path / "feature-summary"
    summary = glm_driver.run([
        "--training-data-directory", str(train),
        "--output-directory", str(out),
        "--task", "LOGISTIC_REGRESSION",
        "--regularization-weights", "1",
        "--max-num-iterations", "10",
        "--selected-features-file", str(sel),
        "--summarization-output-dir", str(summ),
        "--dtype", "float64",
    ])
    # 2 selected features + intercept.
    index = json.loads((out / "feature-index.json").read_text())
    assert len(index) == 3
    recs = list(read_container(summ / "part-00000.avro"))
    assert len(recs) == 3
    by_name = {r["featureName"]: r["metrics"] for r in recs}
    assert {"f0", "f1"} <= set(by_name)
    m = by_name["f0"]
    assert {"max", "min", "mean", "normL1", "normL2", "numNonzeros",
            "variance"} == set(m)
    assert m["numNonzeros"] > 0


def test_glm_driver_profile_trace(tmp_path, rng):
    """--profile-output-dir writes a jax.profiler trace of the train phase."""
    train = tmp_path / "train"
    _write_glm_avro(train, rng, n=60)
    out = tmp_path / "out"
    prof = tmp_path / "profile"
    glm_driver.run([
        "--training-data-directory", str(train),
        "--output-directory", str(out),
        "--task", "LOGISTIC_REGRESSION",
        "--regularization-weights", "1",
        "--max-num-iterations", "5",
        "--profile-output-dir", str(prof),
        "--dtype", "float64",
    ])
    assert prof.exists(), "profiler did not create the trace directory"
    assert any(prof.rglob("*.xplane.pb")), list(prof.rglob("*"))


def _write_sparse_fe_avro(path, rng, n=240, d=40, per_row=4, offset=0):
    """Fixed-effect-only TrainingExampleAvro with density below the dense
    threshold, so ingest takes the CSR layout (the --stream-train sparse
    assembly path)."""
    w = rng.normal(0, 1, d + 1)
    records = []
    for i in range(n):
        idx = rng.choice(d, size=per_row, replace=False)
        vals = rng.normal(0, 1, per_row)
        z = float(vals @ w[idx] + w[-1])
        records.append({
            "uid": f"u{offset + i}",
            "label": float(rng.random() < 1 / (1 + np.exp(-z))),
            "features": [{"name": f"f{j}", "term": None, "value": float(v)}
                         for j, v in zip(idx, vals)],
            "weight": None, "offset": None, "metadataMap": None})
    path.mkdir(parents=True, exist_ok=True)
    write_container(path / "part-00000.avro", schemas.TRAINING_EXAMPLE,
                    records)


_STREAM_BASE = [
    "--task-type", "LOGISTIC_REGRESSION",
    "--fixed-effect-data-configurations", "fixed:global",
    "--fixed-effect-optimization-configurations",
    "fixed:25,1e-7,1.0,1.0,LBFGS,L2",
    "--updating-sequence", "fixed",
]


def _coeff_records(out_dir):
    """Decoded coefficient records — the byte-identity comparison unit
    (the Avro container header embeds a random sync marker, so FILE bytes
    can never match; the records carry the exact f32 coefficient bits)."""
    return list(read_container(
        out_dir / "best" / "fixed-effect" / "fixed" / "coefficients"
        / "part-00000.avro"))


def test_stream_train_resident_model_identical_to_one_shot(tmp_path, rng):
    """--stream-train without --hbm-budget assembles the exact one-shot
    device batch from the streamed ingest: the saved fixed-effect model
    is identical to the one-shot driver's, bit for bit, for BOTH feature
    layouts and for non-block-aligned --batch-rows."""
    for tag, writer in (("sparse", _write_sparse_fe_avro),
                        ("dense", _write_glm_avro)):
        train = tmp_path / tag / "train"
        writer(train, rng, n=220)
        base = ["--train-input-dirs", str(train)] + _STREAM_BASE
        one = tmp_path / tag / "one"
        st = tmp_path / tag / "stream"
        game_training_driver.run(base + ["--output-dir", str(one)])
        summary = game_training_driver.run(
            base + ["--output-dir", str(st), "--stream-train",
                    "--batch-rows", "33"])
        assert _coeff_records(one) == _coeff_records(st), tag
        info = summary["stream_train"]
        assert info["mode"] == "resident-assembled"
        assert info["feeder"]["rows"] == 220
        assert info["feeder"]["batches"] == 7  # ceil(220/33)


def test_stream_train_spill_identical_across_residency(tmp_path, rng):
    """--hbm-budget mode: eviction-forced, python-feeder, zero-prefetch
    runs all write the SAME model bytes as a fully-resident streamed run
    (fixed shard order defines the accumulation); and the result matches
    the one-shot model to f32 accumulation tolerance."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=300)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE
    one = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "one")])
    big = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "big"), "--stream-train",
                "--batch-rows", "64", "--hbm-budget", "64M"])
    small = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "small"), "--stream-train",
                "--batch-rows", "64", "--hbm-budget", "8K",
                "--feeder", "python", "--prefetch-batches", "0"])
    assert big["stream_train"]["cache"]["evictions"] == 0
    assert small["stream_train"]["cache"]["evictions"] > 0
    assert _coeff_records(tmp_path / "big") == \
        _coeff_records(tmp_path / "small")
    ref = {r["name"]: r["value"]
           for r in _coeff_records(tmp_path / "one")[0]["means"]}
    got = {r["name"]: r["value"]
           for r in _coeff_records(tmp_path / "big")[0]["means"]}
    assert set(ref) == set(got)
    np.testing.assert_allclose([got[k] for k in sorted(ref)],
                               [ref[k] for k in sorted(ref)],
                               rtol=1e-3, atol=2e-5)
    assert one["numRows"] == big["numRows"] == 300


def test_stream_train_spill_source_redecode_model_identity(tmp_path, rng):
    """Fully out-of-core epochs: --spill-source redecode (evicted blocks
    dropped, misses re-decode Avro) writes model bytes IDENTICAL to the
    buffer-spill run — for the native and the python feeder — because a
    re-decoded block reconstructs the evicted padded triplet exactly.
    The explicit --spill-dtype f32 spelling equals the default."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=300)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE + [
        "--stream-train", "--batch-rows", "64", "--hbm-budget", "8K"]
    buffer_run = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "buf"),
                "--spill-dtype", "f32"])
    assert buffer_run["stream_train"]["cache"]["evictions"] > 0
    assert buffer_run["stream_train"]["cache"]["spill_bytes_host"] > 0
    ref = _coeff_records(tmp_path / "buf")
    for tag, extra in (("rd", []), ("rd_py", ["--feeder", "python"])):
        out = tmp_path / tag
        summary = game_training_driver.run(
            base + ["--output-dir", str(out),
                    "--spill-source", "redecode"] + extra)
        assert _coeff_records(out) == ref, tag
        info = summary["stream_train"]
        assert info["spill_source"] == "redecode"
        cache = info["cache"]
        assert cache["spill_bytes_host"] == 0  # no host copy at all
        assert cache["redecodes"] == cache["misses"] > 0
        assert cache["bytes_redecoded"] > 0
        assert info["redecode"]["payload_bytes_read"] > 0
        assert info["redecode"]["rows_fetched"] > 0


def test_stream_train_bf16_spill_parity_and_residency_independence(
        tmp_path, rng):
    """Compressed spill: --spill-dtype bf16 (1) is residency-independent
    — two budgets with very different eviction pressure write IDENTICAL
    model bytes (values quantize once at ingest) — (2) matches the
    f32-spill model per-coefficient within the bf16 parity bound, (3)
    retains 1/3 of the f32 host spill bytes and ~1/3 of its per-epoch
    re-upload traffic."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=300)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE + [
        "--stream-train", "--batch-rows", "64"]
    f32 = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "f32"),
                "--hbm-budget", "8K"])
    small = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "bf_small"),
                "--hbm-budget", "8K", "--spill-dtype", "bf16"])
    big = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "bf_big"),
                "--hbm-budget", "64K", "--spill-dtype", "bf16"])
    assert small["stream_train"]["cache"]["evictions"] \
        > big["stream_train"]["cache"]["evictions"]
    assert _coeff_records(tmp_path / "bf_small") == \
        _coeff_records(tmp_path / "bf_big")
    # parity bound vs the f32-spill model: per-coefficient rel error
    ref = {r["name"]: r["value"]
           for r in _coeff_records(tmp_path / "f32")[0]["means"]}
    got = {r["name"]: r["value"]
           for r in _coeff_records(tmp_path / "bf_small")[0]["means"]}
    assert set(ref) == set(got)
    np.testing.assert_allclose([got[k] for k in sorted(ref)],
                               [ref[k] for k in sorted(ref)],
                               rtol=0.1, atol=5e-3)
    c_f32 = f32["stream_train"]["cache"]
    c_bf = small["stream_train"]["cache"]
    assert c_bf["spill_bytes_host"] * 3 == c_f32["spill_bytes_host"]
    assert c_bf["spill_dtype"] == "bf16"
    # same eviction pressure, compact re-uploads: ~1/3 the f32 traffic
    # (not exactly — iteration counts may differ at bf16 precision)
    assert c_bf["bytes_reuploaded"] < 0.5 * c_f32["bytes_reuploaded"]


def test_spill_flags_require_hbm_budget(tmp_path, rng):
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=60)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE + [
        "--stream-train", "--batch-rows", "32"]
    with pytest.raises(ValueError, match="--spill-dtype"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "a"),
                    "--spill-dtype", "bf16"])
    with pytest.raises(ValueError, match="--spill-source"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "b"),
                    "--spill-source", "redecode"])
    # bf16 compresses buffers; redecode keeps none — reject the combo
    with pytest.raises(ValueError, match="pick one"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "c"),
                    "--hbm-budget", "8K", "--spill-dtype", "bf16",
                    "--spill-source", "redecode"])


_GRID_STREAM_BASE = [
    "--task-type", "LOGISTIC_REGRESSION",
    "--fixed-effect-data-configurations", "fixed:global",
    "--fixed-effect-optimization-configurations",
    "fixed:25,1e-7,0.5,1.0,LBFGS,L2|25,1e-7,5.0,1.0,LBFGS,L2"
    "|25,1e-7,50.0,1.0,LBFGS,L2",
    "--updating-sequence", "fixed",
]


def test_grid_batched_sweep_selects_same_model(tmp_path, rng):
    """--grid-batched: 'auto' batches a 3-point λ-grid into one streamed
    sweep that selects the SAME λ as the sequential sweep with
    per-coefficient agreement on the saved model; 'on' with G=1 writes
    model bytes IDENTICAL to the sequential solve (the bitwise gate,
    end to end through the CLI)."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=300)
    base = ["--train-input-dirs", str(train)] + _GRID_STREAM_BASE + [
        "--stream-train", "--batch-rows", "64", "--hbm-budget", "8K"]
    seq = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "seq"),
                "--grid-batched", "off"])
    bat = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "bat")])
    assert seq["stream_train"]["grid_batched"] is False
    assert bat["stream_train"]["grid_batched"] is True
    assert seq["stream_train"]["grid_points"] == \
        bat["stream_train"]["grid_points"] == 3
    assert seq["bestConfigs"] == bat["bestConfigs"]  # selection parity
    ref = {r["name"]: r["value"]
           for r in _coeff_records(tmp_path / "seq")[0]["means"]}
    got = {r["name"]: r["value"]
           for r in _coeff_records(tmp_path / "bat")[0]["means"]}
    assert set(ref) == set(got)
    np.testing.assert_allclose([got[k] for k in sorted(ref)],
                               [ref[k] for k in sorted(ref)],
                               rtol=2e-3, atol=1e-4)
    # the sweep's grid kernels stayed within their compile budgets
    assert any(k.startswith("sharded:grid_") and v > 0
               for k, v in bat["stream_train"]["trace_counts"].items())
    # G=1 forced batched: bitwise model identity with sequential
    g1 = ["--train-input-dirs", str(train)] + _STREAM_BASE + [
        "--stream-train", "--batch-rows", "64", "--hbm-budget", "8K"]
    game_training_driver.run(
        g1 + ["--output-dir", str(tmp_path / "g1seq"),
              "--grid-batched", "off"])
    on = game_training_driver.run(
        g1 + ["--output-dir", str(tmp_path / "g1on"),
              "--grid-batched", "on"])
    assert on["stream_train"]["grid_batched"] is True
    assert _coeff_records(tmp_path / "g1seq") == \
        _coeff_records(tmp_path / "g1on")


def test_grid_batched_flag_validation(tmp_path, rng):
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=60)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE
    with pytest.raises(ValueError, match="--grid-batched applies"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "a"),
                    "--grid-batched", "on"])
    with pytest.raises(ValueError, match="--grid-batched on requires"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "b"),
                    "--stream-train", "--grid-batched", "on"])


def _write_mf_avro(path, rng, n=240, n_users=9, d=6, k_true=2):
    """Linear labels with per-entity rank-k_true coefficient structure —
    the streamed-MF coordinate's training shape (userId in
    metadataMap)."""
    b_true = rng.normal(0, 1, (k_true, d))
    g_true = rng.normal(0, 1, (n_users, k_true))
    coefs = g_true @ b_true
    records = []
    for i in range(n):
        u = int(rng.integers(0, n_users))
        x = rng.normal(0, 1, d)
        yv = float(x @ coefs[u] + rng.normal(0, 0.05))
        records.append({
            "uid": f"r{i}", "label": yv,
            "features": [{"name": f"x{j}", "term": None, "value": float(v)}
                         for j, v in enumerate(x)],
            "weight": None, "offset": None,
            "metadataMap": {"userId": f"user{u}"}})
    path.mkdir(parents=True, exist_ok=True)
    write_container(path / "part-00000.avro", schemas.TRAINING_EXAMPLE,
                    records)


_MF_STREAM_BASE = [
    "--task-type", "LINEAR_REGRESSION",
    "--factored-random-effect-data-configurations",
    "perUser:userId,global,1,-1,-1,-1,identity",
    "--factored-random-effect-optimization-configurations",
    "perUser:20,1e-8,0.001,1.0,LBFGS,L2;20,1e-8,0.001,1.0,LBFGS,L2;2,3",
    "--updating-sequence", "perUser",
]


def _latent_records(out_dir):
    """Decoded latent artifacts — the byte-identity comparison unit for
    MF runs (per-entity gamma + the shared projection B)."""
    base = out_dir / "best" / "random-effect" / "perUser" / "latent"
    return (list(read_container(base / "gamma-latent-factors.avro")),
            list(read_container(base / "projection-latent-factors.avro")))


@pytest.mark.slow
def test_stream_train_mf_identity_across_residency_and_feeder(tmp_path,
                                                              rng):
    """Tentpole acceptance at the CLI: a factor table larger than
    --hbm-budget trains to completion out-of-core, and the saved latent
    artifacts (gamma + B) are IDENTICAL across residency, feeder and
    prefetch configs; the streamed model parity-matches the in-core
    driver's factored coordinate at identical iteration counts."""
    train = tmp_path / "train"
    _write_mf_avro(train, rng)
    base = ["--train-input-dirs", str(train)] + _MF_STREAM_BASE

    resident = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "resident"),
                "--stream-train", "--batch-rows", "64"])
    info = resident["stream_train"]
    assert info["mode"] == "mf-stream"
    assert info["cache"]["evictions"] == 0
    g_res, p_res = _latent_records(tmp_path / "resident")

    spill = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "spill"),
                "--stream-train", "--batch-rows", "64",
                "--hbm-budget", "64"])
    cache = spill["stream_train"]["cache"]
    assert cache["evictions"] > 0 and cache["misses"] > 0
    # the factor table exceeds the budget: out-of-core by construction
    assert cache["peak_device_bytes"] + cache["spill_bytes_host"] > 64
    assert _latent_records(tmp_path / "spill") == (g_res, p_res)

    forced = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "python"),
                "--stream-train", "--batch-rows", "64",
                "--feeder", "python", "--prefetch-batches", "0"])
    assert forced["stream_train"]["feeder"]["decode_path"] == "python"
    assert _latent_records(tmp_path / "python") == (g_res, p_res)

    # in-core parity at identical iteration counts: the one-shot driver
    # trains the same factored coordinate through the estimator
    game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "incore")])
    g_ic, p_ic = _latent_records(tmp_path / "incore")
    b_stream = np.asarray([r["latentFactor"] for r in p_res])
    b_core = np.asarray([r["latentFactor"] for r in p_ic])
    assert b_stream.shape == b_core.shape
    scale = np.max(np.abs(b_core))
    assert np.max(np.abs(b_stream - b_core)) <= 1e-3 * scale
    assert [r["effectId"] for r in g_res] == [r["effectId"] for r in g_ic]


@pytest.mark.slow
def test_stream_train_mf_bf16_and_redecode_tiers(tmp_path, rng):
    """Spill tiers for factors at the CLI: bf16 models are bitwise
    residency-independent and parity-bounded vs f32; redecode keeps
    ZERO host spill bytes, re-derives misses from observations, and
    writes bytes identical to the buffer tier."""
    train = tmp_path / "train"
    _write_mf_avro(train, rng)
    base = ["--train-input-dirs", str(train)] + _MF_STREAM_BASE + [
        "--stream-train", "--batch-rows", "64"]

    f32 = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "f32"),
                "--hbm-budget", "64"])
    lat_f32 = _latent_records(tmp_path / "f32")

    bf_small = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "bf-small"),
                "--hbm-budget", "64", "--spill-dtype", "bf16"])
    bf_big = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "bf-big"),
                "--hbm-budget", "1G", "--spill-dtype", "bf16"])
    assert bf_small["stream_train"]["cache"]["evictions"] > 0
    assert bf_big["stream_train"]["cache"]["evictions"] == 0
    lat_small = _latent_records(tmp_path / "bf-small")
    assert lat_small == _latent_records(tmp_path / "bf-big")
    assert lat_small != lat_f32  # quantized — but parity-bounded:
    b_bf = np.asarray([r["latentFactor"] for r in lat_small[1]])
    b_f = np.asarray([r["latentFactor"] for r in lat_f32[1]])
    assert np.max(np.abs(b_bf - b_f)) <= 0.05 * np.max(np.abs(b_f))

    rd = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "redecode"),
                "--hbm-budget", "64", "--spill-source", "redecode"])
    info = rd["stream_train"]
    assert info["cache"]["spill_bytes_host"] == 0
    assert info["cache"]["redecodes"] > 0
    assert info["redecode"]["payload_bytes_read"] > 0
    assert info["redecode"]["rows_fetched"] > 0
    assert _latent_records(tmp_path / "redecode") == lat_f32


def test_stream_train_mf_schema_grid_and_compile_bounds(tmp_path, rng):
    """MF-mode metrics.json schema (snake_case, plan block, ALX density
    histogram), λ-grid kernel sharing (grid points with one num_factors
    share every compiled kernel — trace counts within the per-bucket
    budgets), and factor-cache registry counters."""
    train = tmp_path / "train"
    _write_mf_avro(train, rng)
    # grid: two λ points at k=3 (share one objective/cache) + one at
    # k=2 (its own cache -> the cache_by_num_factors block)
    grid = ("perUser:20,1e-8,0.001,1.0,LBFGS,L2;20,1e-8,0.001,1.0,"
            "LBFGS,L2;2,3|15,1e-8,0.1,1.0,LBFGS,L2;15,1e-8,0.1,1.0,"
            "LBFGS,L2;2,3|10,1e-8,0.001,1.0,LBFGS,L2;10,1e-8,0.001,"
            "1.0,LBFGS,L2;1,2")
    summary = game_training_driver.run([
        "--train-input-dirs", str(train),
        "--task-type", "LINEAR_REGRESSION",
        "--factored-random-effect-data-configurations",
        "perUser:userId,global,1,-1,-1,-1,identity",
        "--factored-random-effect-optimization-configurations", grid,
        "--updating-sequence", "perUser",
        "--output-dir", str(tmp_path / "out"),
        "--stream-train", "--batch-rows", "64", "--hbm-budget", "64"])
    assert summary["numCombos"] == 3
    info = summary["stream_train"]
    assert set(info) == {"mode", "batch_rows", "hbm_budget_bytes",
                         "mesh_devices", "mesh_shape", "spill_dtype",
                         "spill_source", "feeder", "cache", "plan",
                         "trace_budgets", "trace_counts",
                         "cache_by_num_factors"}
    # every factor cache in a multi-k grid stays observable post-run
    assert set(info["cache_by_num_factors"]) == {"2", "3"}
    assert info["cache_by_num_factors"]["3"] == info["cache"]
    assert info["mode"] == "mf-stream"
    assert info["mesh_devices"] is None
    assert info["plan"]["entities"] == 9
    assert info["plan"]["shards"] >= 1
    assert sum(info["plan"]["obs_bucket_histogram"].values()) == 9
    # compile bound: every mf kernel within its observed-bucket budget,
    # TWO grid points deep (shared objective -> shared executables)
    for name, count in info["trace_counts"].items():
        if name in info["trace_budgets"]:
            assert count <= info["trace_budgets"][name], (name, count)
    m = summary["telemetry"]["metrics"]
    assert m["counters"]["data.factor_cache.evictions"] > 0
    assert m["gauges"]["data.factor_cache.peak_device_bytes"] > 0
    # mf sweeps rode the solver-iteration telemetry (B refits)
    assert m["counters"]["training.solver_iterations"] >= 1


def test_stream_train_mf_flag_validation(tmp_path, rng):
    train = tmp_path / "train"
    _write_mf_avro(train, rng, n=60)
    base = ["--train-input-dirs", str(train)] + _MF_STREAM_BASE
    with pytest.raises(ValueError, match="mesh"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "a"), "--stream-train",
                    "--batch-rows", "32", "--hbm-budget", "8K",
                    "--mesh-devices", "1"])
    # a plain random effect still cannot stream-train
    with pytest.raises(ValueError, match="fixed-effect or factored"):
        game_training_driver.run([
            "--train-input-dirs", str(train),
            "--task-type", "LINEAR_REGRESSION",
            "--random-effect-data-configurations",
            "re:userId,global,1,-1,-1,-1",
            "--random-effect-optimization-configurations",
            "re:10,1e-7,1.0,1.0,LBFGS,L2",
            "--updating-sequence", "re",
            "--output-dir", str(tmp_path / "b"), "--stream-train"])


def test_stream_train_mesh_model_identical_across_mesh_sizes(tmp_path,
                                                             rng):
    """Tentpole acceptance: --mesh-devices 1 writes the PR-5
    single-device fold's model bit for bit, and mesh sizes {2, 4} write
    byte-identical model artifacts to each other (and, by the ordered
    shard-order combine, to the 1-device fold), with compile counts
    bounded per bucket through the TracingGuard."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=300)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE + [
        "--stream-train", "--batch-rows", "64", "--hbm-budget", "8K"]
    no_mesh = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "nomesh")])
    ref = _coeff_records(tmp_path / "nomesh")
    for n_dev in (1, 2, 4):
        out = tmp_path / f"mesh{n_dev}"
        summary = game_training_driver.run(
            base + ["--output-dir", str(out),
                    "--mesh-devices", str(n_dev)])
        assert _coeff_records(out) == ref, n_dev
        info = summary["stream_train"]
        assert info["mesh_devices"] == n_dev
        assert info["cache"]["mesh_devices"] == (n_dev if n_dev > 1
                                                 else None)
        assert info["cache"]["evictions"] > 0, n_dev
        for name, count in info["trace_counts"].items():
            assert count <= info["trace_budgets"][name], (n_dev, name)
        if n_dev > 1:
            # per-device kernels registered; budget binds PER device
            assert any(k.startswith("sharded:init@d")
                       for k in info["trace_counts"])
            assert len(info["cache"]["per_device_bytes"]) == n_dev


def test_mesh_devices_flag_validation(tmp_path, rng):
    """--mesh-devices composes only with the sharded streaming solve:
    it needs --stream-train, > 1 needs --hbm-budget, and more devices
    than the host exposes fails with the mesh builder's error."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=60)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE
    with pytest.raises(ValueError, match="--stream-train"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "a"),
                    "--mesh-devices", "2"])
    with pytest.raises(ValueError, match="--hbm-budget"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "b"), "--stream-train",
                    "--mesh-devices", "2"])
    with pytest.raises(ValueError, match="devices"):
        game_training_driver.run(
            base + ["--output-dir", str(tmp_path / "c"), "--stream-train",
                    "--hbm-budget", "8K", "--mesh-devices", "64"])
    # N=1 composes with BOTH modes (it is the single-device fold)
    summary = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "d"), "--stream-train",
                "--mesh-devices", "1", "--batch-rows", "32"])
    assert summary["stream_train"]["mesh_devices"] == 1


def _assert_stream_train_telemetry(out_dir, summary, feeder):
    info = summary["stream_train"]
    assert info["feeder"]["decode_path"] == feeder
    for key in ("mode", "batch_rows", "hbm_budget_bytes", "mesh_devices",
                "spill_dtype", "spill_source", "feeder", "cache"):
        assert key in info, key
    if info["cache"] is not None:
        for key in ("hits", "misses", "evictions", "bytes_reuploaded",
                    "peak_device_bytes", "bucket_shapes", "mesh_devices",
                    "per_device_bytes", "spill_dtype", "spill_source",
                    "spill_bytes_host", "spill_bytes_written",
                    "redecodes", "bytes_redecoded"):
            assert key in info["cache"], key
        assert "trace_budgets" in info and "trace_counts" in info
        for name, count in info["trace_counts"].items():
            assert count <= info["trace_budgets"][name], name
    # the deprecated camelCase alias is gone (rode one release behind)
    assert "streamTrain" not in summary
    # the telemetry must round-trip through the metrics.json artifact
    on_disk = json.loads((out_dir / "metrics.json").read_text())
    assert on_disk["stream_train"] == json.loads(json.dumps(info))
    assert "streamTrain" not in on_disk


def test_stream_train_smoke_python_feeder(tmp_path, rng):
    """Tier-1 smoke: end-to-end --stream-train on a tiny generated Avro
    file with the forced-python feeder, asserting metrics.json telemetry
    keys, in both resident and spill modes."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=90)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE
    s_res = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "res"), "--stream-train",
                "--batch-rows", "32", "--feeder", "python"])
    _assert_stream_train_telemetry(tmp_path / "res", s_res, "python")
    s_spill = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "spill"), "--stream-train",
                "--batch-rows", "32", "--feeder", "python",
                "--hbm-budget", "4K"])
    _assert_stream_train_telemetry(tmp_path / "spill", s_spill, "python")
    assert s_spill["stream_train"]["mode"] == "spill"


@pytest.mark.native_decoder
def test_stream_train_smoke_native_feeder(tmp_path, rng):
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=90)
    base = ["--train-input-dirs", str(train)] + _STREAM_BASE
    summary = game_training_driver.run(
        base + ["--output-dir", str(tmp_path / "out"), "--stream-train",
                "--batch-rows", "32", "--feeder", "native",
                "--hbm-budget", "1M"])
    _assert_stream_train_telemetry(tmp_path / "out", summary, "native")


def test_stream_train_streamed_validation_matches_one_shot(tmp_path, rng):
    """Validation goes through StreamingGameScorer.score_container_stream
    (bounded by --batch-rows) and reproduces the one-shot driver's
    validation metrics; grid selection uses the streamed metric."""
    train = tmp_path / "train"
    valid = tmp_path / "valid"
    _write_sparse_fe_avro(train, rng, n=300)
    _write_sparse_fe_avro(valid, rng, n=130, offset=300)
    grid = [
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:25,1e-7,10.0,1.0,LBFGS,L2|25,1e-7,0.1,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--evaluators", "AUC,LOGISTIC_LOSS",
        "--train-input-dirs", str(train),
        "--validate-input-dirs", str(valid),
    ]
    one = game_training_driver.run(grid + ["--output-dir",
                                           str(tmp_path / "one")])
    st = game_training_driver.run(
        grid + ["--output-dir", str(tmp_path / "stream"), "--stream-train",
                "--batch-rows", "48"])
    assert st["numCombos"] == one["numCombos"] == 2
    assert st["bestConfigs"] == one["bestConfigs"]
    for name, v in one["validationHistory"][-1].items():
        np.testing.assert_allclose(st["validationHistory"][-1][name], v,
                                   rtol=1e-6, atol=1e-7)
    # the winning streamed model is the winning one-shot model, exactly
    assert _coeff_records(tmp_path / "one") == \
        _coeff_records(tmp_path / "stream")


def test_stream_train_rejects_random_effects(tmp_path, rng):
    from photon_ml_tpu import telemetry

    train = tmp_path / "train"
    _write_game_avro(train, rng, n=40)
    with pytest.raises(ValueError, match="one fixed-effect"):
        game_training_driver.run([
            "--train-input-dirs", str(train),
            "--output-dir", str(tmp_path / "o"),
            "--task-type", "LOGISTIC_REGRESSION",
            "--fixed-effect-data-configurations", "fixed:global",
            "--fixed-effect-optimization-configurations",
            "fixed:10,1e-6,1.0,1.0,LBFGS,L2",
            "--random-effect-data-configurations",
            "perUser:userId,global,4,-1,-1,-1",
            "--random-effect-optimization-configurations",
            "perUser:10,1e-6,1.0,1.0,LBFGS,L2",
            "--updating-sequence", "fixed,perUser",
            "--stream-train"])
    # A failed run must not leave the process-wide recorder armed.
    assert not telemetry.enabled()


class RecordingListener(EventListener):
    """Registered BY NAME from the driver (utils/events.py reflective
    registration). State goes through a file named by an env var —
    importlib re-imports this module under its dotted name, so a
    class-level list would live on a DIFFERENT class object than the
    one pytest asserts on."""

    def on_event(self, event):
        import dataclasses
        import os

        with open(os.environ["PHOTON_TEST_EVENT_LOG"], "a") as f:
            f.write(json.dumps({"type": type(event).__name__,
                                **dataclasses.asdict(event)}) + "\n")


def test_stream_train_emits_training_events(tmp_path, rng, monkeypatch):
    """Satellite: --stream-train emits TrainingStart / per-λ
    PhotonOptimizationLog / TrainingFinish through the EventEmitter
    (listener registration existed; the streamed path never emitted)."""
    log = tmp_path / "events.jsonl"
    monkeypatch.setenv("PHOTON_TEST_EVENT_LOG", str(log))
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=90)
    game_training_driver.run([
        "--train-input-dirs", str(train),
        "--output-dir", str(tmp_path / "out"),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:25,1e-7,1.0,1.0,LBFGS,L2|25,1e-7,0.1,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--stream-train", "--batch-rows", "32",
        "--job-name", "stream-events-job",
        "--event-listeners", "tests.test_cli_drivers.RecordingListener",
    ])
    evs = [json.loads(line) for line in log.read_text().splitlines()]
    assert evs[0]["type"] == TrainingStartEvent.__name__
    assert evs[0]["job_name"] == "stream-events-job"
    opt = [e for e in evs
           if e["type"] == PhotonOptimizationLogEvent.__name__]
    assert sorted(e["reg_weight"] for e in opt) == [0.1, 1.0]  # per λ
    for e in opt:
        assert e["iterations"] >= 1
        assert np.isfinite(e["final_value"])
        assert e["converged_reason"]
    assert evs[-1]["type"] == TrainingFinishEvent.__name__
    assert evs[-1]["job_name"] == "stream-events-job"
    assert evs[-1]["duration_seconds"] > 0


def test_stream_train_snake_schema_and_trace(tmp_path, rng):
    """Satellite + tentpole acceptance: the metrics.json stream block is
    snake_case (``stream_train``); the deprecated camelCase
    ``streamTrain`` alias — kept one release behind by PR 6 — is now
    REMOVED. The run writes a Perfetto-loadable trace and a telemetry
    block whose stage attribution explains >= 90% of the end-to-end
    wall time, with solver-iteration timing from the histogram."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=120)
    trace_path = tmp_path / "trace.json"
    summary = game_training_driver.run(
        ["--train-input-dirs", str(train)] + _STREAM_BASE + [
            "--output-dir", str(tmp_path / "out"), "--stream-train",
            "--batch-rows", "32", "--hbm-budget", "8K",
            "--trace-out", str(trace_path)])

    info = summary["stream_train"]
    assert set(info) == {"mode", "batch_rows", "hbm_budget_bytes",
                         "mesh_devices", "mesh_shape", "spill_dtype",
                         "spill_source", "feeder", "cache", "grid_batched",
                         "grid_points", "trace_budgets", "trace_counts"}
    assert info["batch_rows"] == 32
    assert info["mode"] == "spill"
    assert info["mesh_devices"] is None
    assert info["spill_dtype"] == "f32"
    assert info["spill_source"] == "buffer"
    assert info["grid_batched"] is False  # single-λ grid stays sequential
    assert info["grid_points"] == 1
    assert "streamTrain" not in summary  # deprecated alias removed

    tele = summary["telemetry"]
    assert tele["attributed_wall_frac"] >= 0.9
    assert tele["attributed_wall_seconds"] <= tele["wall_seconds"] * 1.01
    att = tele["stage_attribution"]
    for stage in ("driver", "build_index", "ingest", "solve", "finalize",
                  "solver_step", "accumulate", "decode"):
        assert stage in att, stage
    m = tele["metrics"]
    assert m["counters"]["training.solver_iterations"] >= 1
    it_hist = m["histograms"]["training.iteration_seconds"]
    assert it_hist["count"] >= 1 and it_hist["p50"] is not None
    assert m["counters"]["data.shard_cache.evictions"] > 0
    # the satellite gauge: host spill bytes visible in the registry,
    # equal to the cache's own accounting
    assert m["gauges"]["data.shard_cache.spill_bytes_host"] == \
        info["cache"]["spill_bytes_host"] > 0
    assert m["counters"]["data.shard_cache.spill_bytes_written"] > 0

    doc = json.loads(trace_path.read_text())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    assert {"ingest", "solve", "solver_step", "accumulate"} <= names
    assert all(e["dur"] >= 0 for e in xs)
    # The on-disk metrics.json carries the same telemetry block.
    on_disk = json.loads((tmp_path / "out" / "metrics.json").read_text())
    assert on_disk["stream_train"] == json.loads(json.dumps(info))
    assert on_disk["telemetry"]["attributed_wall_frac"] >= 0.9


def test_scoring_stream_trace_latency_and_schema(tmp_path, rng):
    """Tentpole acceptance, serving side: --stream writes a
    Perfetto-loadable trace, reports request-latency P50/P99 from the
    histogram, carries snake_case key aliases, and its stage attribution
    explains >= 90% of wall time."""
    model_dir, valid = _train_small_game(tmp_path, rng)
    trace_path = tmp_path / "trace.json"
    out = tmp_path / "score-out"
    summary = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(out),
        "--stream", "--batch-rows", "33",
        "--trace-out", str(trace_path),
    ])
    # snake_case aliases ride beside the deprecated camelCase keys.
    assert summary["num_rows"] == summary["numRows"] == 140
    assert summary["num_batches"] == summary["numBatches"]
    assert summary["batch_rows"] == summary["batchRows"] == 33
    assert summary["scoring_path"] == summary["scoringPath"]
    assert summary["total_seconds"] == summary["totalSeconds"]

    lat = summary["engine"]["request_latency_seconds"]
    assert lat["count"] >= summary["numBatches"]
    assert lat["p50"] is not None and lat["p99"] is not None
    assert 0 < lat["p50"] <= lat["p99"]

    tele = summary["telemetry"]
    assert tele["attributed_wall_frac"] >= 0.9
    m = tele["metrics"]
    assert m["counters"]["serving.rows_scored"] == 140
    assert m["counters"]["serving.dispatches"] >= summary["numBatches"]

    doc = json.loads(trace_path.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"score", "decode", "featureize", "dispatch"} <= names
    # decode ran on the prefetch thread: more than one trace track.
    tids = {e["tid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(tids) >= 2
    on_disk = json.loads((out / "metrics.json").read_text())
    assert on_disk["telemetry"]["metrics"]["counters"][
        "serving.rows_scored"] == 140


def test_multihost_initialize_noop_single_host():
    from photon_ml_tpu.parallel import initialize_multihost, is_primary_host

    assert initialize_multihost() is False  # no coordinator env -> no-op
    assert is_primary_host() is True


def test_glm_driver_bf16_feature_storage(tmp_path, rng):
    """--feature-storage-dtype bfloat16 trains end-to-end and reaches the
    same validation quality as full-width storage (predictions carry
    bf16's ~3 digits; AUC is insensitive at this scale)."""
    train = tmp_path / "train"
    valid = tmp_path / "valid"
    w_true = rng.normal(0, 1, 6)
    _write_glm_avro(train, rng, n=300, w=w_true)
    _write_glm_avro(valid, rng, n=100, w=w_true)

    def run(extra):
        out = tmp_path / ("out-" + ("bf16" if extra else "f32"))
        summary = glm_driver.run([
            "--training-data-directory", str(train),
            "--validating-data-directory", str(valid),
            "--output-directory", str(out),
            "--task", "LOGISTIC_REGRESSION",
            "--regularization-weights", "1",
            "--max-num-iterations", "60",
        ] + extra)
        return summary["validationMetrics"]["1.0"]["AUC"]

    # The flag must actually reach the ingest chooser THROUGH the driver:
    # capture what train_glm_models hands to device_batch.
    import jax.numpy as jnp

    from photon_ml_tpu.estimators import model_training

    seen = []
    orig = model_training.device_batch

    def spy(*a, **kw):
        seen.append(kw.get("storage_dtype"))
        return orig(*a, **kw)

    model_training.device_batch, saved = spy, orig
    try:
        auc32 = run([])
        auc16 = run(["--feature-storage-dtype", "bfloat16"])
    finally:
        model_training.device_batch = saved
    assert auc32 > 0.6  # both models genuinely learned
    assert abs(auc16 - auc32) < 0.02
    assert seen == [None, jnp.bfloat16]
