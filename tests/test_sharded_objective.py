"""ops/sharded_objective.py + the streaming solvers: the out-of-core
numeric contract.

- A single-shard decomposition reproduces the one-shot solver-path
  formulas (`value_from_margins`/`gradient_from_margins`) BIT FOR BIT in
  f32, and the streaming L-BFGS then reproduces the fused
  `minimize_lbfgs_glm` solution bit for bit.
- Any fixed multi-shard decomposition is deterministic and
  residency-independent: resident replay, eviction-forced spill replay,
  and prefetch depths all produce identical bits.
- Compile counts stay within the per-bucket kernel budgets, asserted
  through the TracingGuard.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.shard_cache import DeviceShardCache
from photon_ml_tpu.ops.features import csr_from_scipy
from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.sharded_objective import ShardedGLMObjective
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.glm_lbfgs import (
    minimize_lbfgs_glm,
    minimize_lbfgs_glm_streaming,
)
from photon_ml_tpu.optimization.tron import (
    minimize_tron,
    minimize_tron_streaming,
)
from photon_ml_tpu.types import TaskType

from tests.test_shard_cache import FakeStream


@pytest.fixture
def problem(rng):
    n, d = 1003, 41
    X = sp.random(n, d, density=0.1, random_state=11, format="csr")
    X.data[:] = rng.normal(0, 1, X.nnz)
    y = (rng.random(n) < 0.5).astype(float)
    off = rng.normal(0, 0.1, n)
    w = rng.gamma(1.0, 1.0, n)
    return X, y, off, w


def _batch(X, y, off, w, dtype=jnp.float32):
    n = X.shape[0]
    return GLMBatch(
        csr_from_scipy(X, dtype=dtype), jnp.asarray(y, dtype),
        jnp.asarray(off, dtype), jnp.asarray(w, dtype))


def _sharded(X, y, off, w, batch_rows, budget=None, obj=None):
    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, batch_rows, off, w), "g",
        hbm_budget_bytes=budget)
    if obj is None:
        obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    return ShardedGLMObjective(obj, cache)


def _bits(x):
    return np.asarray(x).tobytes()


def test_single_shard_value_grad_bitwise(problem, rng):
    """The acceptance contract: streamed (value, gradient) == one-shot
    GLMObjective on the same data, bitwise, f32, fixed shard order."""
    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    batch = _batch(X, y, off, w)
    sobj = _sharded(X, y, off, w, batch_rows=X.shape[0], obj=obj)
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.7, jnp.float32)

    z = obj.margins(coef, batch)
    f_ref = obj.value_from_margins(z, jnp.vdot(coef, coef), batch, l2)
    g_ref = obj.gradient_from_margins(coef, z, batch, l2)
    z_list, f, g = sobj.margins_value_grad(coef, l2)
    assert _bits(f) == _bits(f_ref)
    assert _bits(g) == _bits(g_ref)
    # per-row margins are row-local -> bitwise on the true rows
    n = X.shape[0]
    assert _bits(z_list[0][:n]) == _bits(z)


def test_single_shard_normalized_grad_bitwise(problem, rng):
    """Apex-applied factor/shift chain == the per-batch _jt_product chain
    for a single shard (same expression order)."""
    X, y, off, w = problem
    d = X.shape[1]
    norm = NormalizationContext(
        factors=jnp.asarray(rng.uniform(0.5, 2.0, d), jnp.float32),
        shifts=jnp.asarray(rng.normal(0, 0.1, d), jnp.float32),
        intercept_id=-1)
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION), norm)
    batch = _batch(X, y, off, w)
    sobj = _sharded(X, y, off, w, batch_rows=X.shape[0], obj=obj)
    coef = jnp.asarray(rng.normal(0, 0.3, d), jnp.float32)
    l2 = jnp.asarray(0.3, jnp.float32)
    z = obj.margins(coef, batch)
    _, f, g = sobj.margins_value_grad(coef, l2)
    assert _bits(f) == _bits(
        obj.value_from_margins(z, jnp.vdot(coef, coef), batch, l2))
    assert _bits(g) == _bits(obj.gradient_from_margins(coef, z, batch, l2))


def test_multi_shard_close_and_deterministic(problem, rng):
    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    batch = _batch(X, y, off, w)
    sobj = _sharded(X, y, off, w, batch_rows=128, obj=obj)
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.7, jnp.float32)
    f1, g1 = sobj.value_and_grad(coef, l2)
    f2, g2 = sobj.value_and_grad(coef, l2)
    assert _bits(f1) == _bits(f2) and _bits(g1) == _bits(g2)
    z = obj.margins(coef, batch)
    f_ref = obj.value_from_margins(z, jnp.vdot(coef, coef), batch, l2)
    g_ref = obj.gradient_from_margins(coef, z, batch, l2)
    np.testing.assert_allclose(np.asarray(f1), np.asarray(f_ref),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g_ref),
                               rtol=2e-4, atol=1e-6)


def test_spill_replay_bitwise_matches_resident(problem, rng):
    """Eviction/re-upload and prefetch depth can never change a bit of
    any accumulated quantity — the spill-mode model-identity guarantee."""
    X, y, off, w = problem
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.7, jnp.float32)
    resident = _sharded(X, y, off, w, batch_rows=128)
    fr, gr = resident.value_and_grad(coef, l2)
    block_bytes = max(e.feature_bytes for e in resident.cache.entries)
    for budget, depth in [(block_bytes, 2), (2 * block_bytes, 0),
                          (2 * block_bytes, 3)]:
        spill = _sharded(X, y, off, w, batch_rows=128, budget=budget)
        spill.cache.prefetch_depth = depth
        fs, gs = spill.value_and_grad(coef, l2)
        assert _bits(fs) == _bits(fr)
        assert _bits(gs) == _bits(gr)
        assert spill.cache.stats()["evictions"] > 0


def test_hvp_single_shard_bitwise_and_multi_close(problem, rng):
    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    batch = _batch(X, y, off, w)
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    vec = jnp.asarray(rng.normal(0, 1.0, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.4, jnp.float32)

    z = obj.margins(coef, batch)
    d2 = obj.curvature_from_margins(z, batch)
    ref = obj.hessian_vector_from_margins(vec, d2, batch, l2)

    s1 = _sharded(X, y, off, w, batch_rows=X.shape[0], obj=obj)
    z1, _, _ = s1.margins_value_grad(coef, l2)
    hv1 = s1.hessian_vector(vec, s1.curvature_list(z1), l2)
    assert _bits(hv1) == _bits(ref)

    sm = _sharded(X, y, off, w, batch_rows=128, obj=obj)
    zm, _, _ = sm.margins_value_grad(coef, l2)
    hvm = sm.hessian_vector(vec, sm.curvature_list(zm), l2)
    np.testing.assert_allclose(np.asarray(hvm), np.asarray(ref),
                               rtol=2e-4, atol=1e-6)


def test_streaming_lbfgs_single_shard_bitwise(problem):
    """The full streamed solve reproduces the fused lax.while_loop
    solver's iterate trajectory exactly when the decomposition is one
    shard — every mirrored expression lines up."""
    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    batch = _batch(X, y, off, w)
    sobj = _sharded(X, y, off, w, batch_rows=X.shape[0], obj=obj)
    x0 = jnp.zeros(X.shape[1], jnp.float32)
    l2 = jnp.asarray(0.5, jnp.float32)
    ref = minimize_lbfgs_glm(obj, batch, x0, l2, max_iter=30)
    got = minimize_lbfgs_glm_streaming(sobj, x0, l2, max_iter=30)
    assert int(ref.iterations) == int(got.iterations)
    assert int(ref.reason) == int(got.reason)
    assert _bits(ref.x) == _bits(got.x)


def test_streaming_lbfgs_multi_shard_close_and_spill_identical(problem):
    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    batch = _batch(X, y, off, w)
    x0 = jnp.zeros(X.shape[1], jnp.float32)
    l2 = jnp.asarray(0.5, jnp.float32)
    ref = minimize_lbfgs_glm(obj, batch, x0, l2, max_iter=30)
    sm = _sharded(X, y, off, w, batch_rows=128)
    got = minimize_lbfgs_glm_streaming(sm, x0, l2, max_iter=30)
    # Per-iteration ulp differences compound over ~30 iterations near a
    # flat optimum: coefficients agree to ~1e-3 absolute, and the
    # objective values agree tightly.
    np.testing.assert_allclose(np.asarray(got.x), np.asarray(ref.x),
                               atol=2e-3)
    f_ref = obj.value_from_margins(
        obj.margins(ref.x, batch), jnp.vdot(ref.x, ref.x), batch, l2)
    f_got = obj.value_from_margins(
        obj.margins(got.x, batch), jnp.vdot(got.x, got.x), batch, l2)
    np.testing.assert_allclose(np.asarray(f_got), np.asarray(f_ref),
                               rtol=1e-5)
    block_bytes = max(e.feature_bytes for e in sm.cache.entries)
    ssp = _sharded(X, y, off, w, batch_rows=128, budget=block_bytes)
    spill = minimize_lbfgs_glm_streaming(ssp, x0, l2, max_iter=30)
    assert _bits(spill.x) == _bits(got.x)
    assert ssp.cache.stats()["evictions"] > 0


def test_streaming_tron_matches_fused(problem):
    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    batch = _batch(X, y, off, w)
    x0 = jnp.zeros(X.shape[1], jnp.float32)
    l2 = jnp.asarray(0.5, jnp.float32)
    ref = minimize_tron(obj.value, x0, args=(batch, l2), max_iter=12,
                        make_hvp=obj.make_tron_hvp)
    s1 = _sharded(X, y, off, w, batch_rows=X.shape[0], obj=obj)
    got1 = minimize_tron_streaming(s1, x0, l2, max_iter=12)
    # TRON's fused path derives its gradient via jax.value_and_grad (AD
    # association differs in ulps), so single-shard parity is allclose,
    # not bitwise; trajectory-level agreement is asserted via iterations.
    assert int(got1.iterations) == int(ref.iterations)
    np.testing.assert_allclose(np.asarray(got1.x), np.asarray(ref.x),
                               rtol=1e-4, atol=1e-6)
    sm = _sharded(X, y, off, w, batch_rows=128)
    gotm = minimize_tron_streaming(sm, x0, l2, max_iter=12)
    np.testing.assert_allclose(np.asarray(gotm.x), np.asarray(ref.x),
                               rtol=1e-3, atol=2e-5)
    block_bytes = max(e.feature_bytes for e in sm.cache.entries)
    ssp = _sharded(X, y, off, w, batch_rows=128, budget=block_bytes)
    gots = minimize_tron_streaming(ssp, x0, l2, max_iter=12)
    assert _bits(gots.x) == _bits(gotm.x)


def test_trace_budget_enforced(problem, rng):
    """Compile count <= kernel families x bucket shapes, via the guard;
    replays and lambda-grid reuse add NO traces."""
    X, y, off, w = problem
    sobj = _sharded(X, y, off, w, batch_rows=128)
    x0 = jnp.zeros(X.shape[1], jnp.float32)
    for l2 in (0.1, 1.0, 10.0):
        minimize_lbfgs_glm_streaming(sobj, x0, jnp.asarray(l2, jnp.float32),
                                     max_iter=8)
    minimize_tron_streaming(sobj, x0, jnp.asarray(0.5, jnp.float32),
                            max_iter=4)
    sobj.assert_trace_budget()
    counts = sobj.guard.counts()
    budgets = sobj.trace_budgets()
    buckets = len(sobj.cache.bucket_shapes())
    assert buckets >= 1
    for name, c in counts.items():
        assert c <= budgets[name], (name, c, budgets[name])


def test_trace_budget_trips_on_violation(problem):
    """The guard genuinely fires: inflate a kernel's trace count past
    its budget by calling it at a foreign shape."""
    from photon_ml_tpu.utils.tracing_guard import RetraceError

    X, y, off, w = problem
    sobj = _sharded(X, y, off, w, batch_rows=X.shape[0])
    coef = jnp.zeros(X.shape[1], jnp.float32)
    sobj.value_and_grad(coef, 0.1)
    e = sobj.cache.entries[0]
    for rows in (8, 16, 32):  # foreign shapes -> fresh traces
        z = jnp.zeros(rows, jnp.float32)
        sobj._k_curv(z, jnp.zeros(rows, jnp.float32),
                     jnp.zeros(rows, jnp.float32))
    assert e is not None
    with pytest.raises(RetraceError, match="trace budgets"):
        sobj.assert_trace_budget()


def test_streaming_coordinate_scope_errors(problem):
    from photon_ml_tpu.algorithm.coordinates import (
        StreamingFixedEffectCoordinate,
    )

    X, y, off, w = problem
    cache = DeviceShardCache.from_stream(FakeStream(X, y, 200, off, w),
                                         "g")
    def coord(cfg):
        return StreamingFixedEffectCoordinate(
            name="fe", cache=cache, feature_shard_id="g",
            task_type=TaskType.LOGISTIC_REGRESSION,
            config=GLMOptimizationConfiguration.parse(cfg))

    with pytest.raises(ValueError, match="L2 only"):
        coord("10,1e-6,1.0,1.0,LBFGS,L1")
    with pytest.raises(ValueError, match="down-sampling"):
        coord("10,1e-6,1.0,0.5,LBFGS,L2")
    model, result = coord("10,1e-6,1.0,1.0,LBFGS,L2").solve()
    assert model.glm.coefficients.means.shape == (X.shape[1],)
    assert int(result.iterations) > 0


def test_redecode_replay_bitwise_matches_resident(problem, rng):
    """The fully out-of-core tier: a redecode cache (evicted blocks
    dropped, misses re-fetched) produces (value, gradient) bitwise
    equal to the fully resident fold — the re-decoded padded triplet
    IS the ingested one."""
    X, y, off, w = problem
    obj = GLMObjective(loss_for_task(TaskType.LOGISTIC_REGRESSION))
    resident = _sharded(X, y, off, w, batch_rows=200, obj=obj)
    block = max(e.feature_bytes for e in resident.cache.entries)

    from photon_ml_tpu.data.game_data import GameDataset

    def fetch(row_start, n_rows):
        s = slice(row_start, row_start + n_rows)
        Xc = sp.csr_matrix(X)
        return GameDataset.build(responses=y[s],
                                 feature_shards={"g": Xc[s]},
                                 offsets=off[s], weights=w[s])

    cache = DeviceShardCache.from_stream(
        FakeStream(X, y, 200, off, w), "g", hbm_budget_bytes=block,
        spill_source="redecode", redecode_fetch=fetch)
    sobj = ShardedGLMObjective(obj, cache)
    coef = jnp.asarray(rng.normal(0, 0.3, X.shape[1]), jnp.float32)
    l2 = jnp.asarray(0.7, jnp.float32)
    f_res, g_res = resident.value_and_grad(coef, l2)
    for _ in range(2):  # two epochs: steady-state misses too
        f, g = sobj.value_and_grad(coef, l2)
        assert _bits(f) == _bits(f_res)
        assert _bits(g) == _bits(g_res)
    assert cache.stats()["redecodes"] > 0
    assert cache.spill_bytes_host == 0


def test_restore_dtype_contract_rejects_leaked_bf16(problem, rng):
    """The runtime half of the restore-dtype contract: a feature block
    that reaches the accumulate as bf16 (i.e. a spill buffer leaked
    past restore_spilled_features) fails loudly instead of silently
    tracing second executables per bucket."""
    import dataclasses as dc

    from photon_ml_tpu.ops.features import CSRFeatures

    X, y, off, w = problem
    sobj = _sharded(X, y, off, w, batch_rows=200)
    e = sobj.cache.entries[0]
    leaked = CSRFeatures(e.feats.values.astype(jnp.bfloat16),
                         e.feats.col_ids, e.feats.row_ids,
                         e.rows_bucket, sobj.cache.n_features)
    sobj.cache._entries[0] = dc.replace(e, feats=leaked)
    coef = jnp.zeros((X.shape[1],), jnp.float32)
    with pytest.raises(TypeError, match="restore_spilled_features"):
        sobj.value_and_grad(coef, jnp.asarray(0.1, jnp.float32))
