"""Parallel sharded ingest (data/shard_planner.py, data/parallel_ingest.py,
data/device_feed.py): worker-count invariance (byte-identical datasets,
values AND row order), graceful fallback without the C decoder, and clean
shard-naming errors on corrupt input instead of a hung pool."""

import numpy as np
import pytest

from photon_ml_tpu.data.avro_reader import (
    read_game_dataset,
    read_labeled_points,
)
from photon_ml_tpu.data.parallel_ingest import (
    IngestShardError,
    parallel_fast_ingest,
    resolve_ingest_workers,
)
from photon_ml_tpu.data.shard_planner import (
    plan_shards,
    scan_container_blocks,
)
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container


def _write_training_file(path, n, rng, n_features=60, per_row=6,
                         sync_interval=2048):
    """Many-block TrainingExampleAvro file with every optional field
    exercised (null/absent uids, weights, offsets)."""
    recs = []
    for i in range(n):
        cols = rng.choice(n_features, size=per_row, replace=False)
        recs.append({
            "uid": f"u{i}" if i % 3 else None,
            "label": float(i % 2),
            "features": [
                {"name": f"f{c}", "term": "t" if c % 2 else None,
                 "value": float(rng.normal())} for c in cols],
            "weight": 2.0 if i % 5 == 0 else None,
            "offset": 0.25 if i % 7 == 0 else None,
            "metadataMap": {"userId": f"user{i % 13}",
                            "itemId": f"item{i % 31}"},
        })
    write_container(path, schemas.TRAINING_EXAMPLE, recs,
                    sync_interval=sync_interval)
    return recs


@pytest.fixture
def training_file(tmp_path, rng):
    p = tmp_path / "train.avro"
    _write_training_file(p, 3000, rng)
    return p


def _assert_datasets_identical(a, b):
    assert np.array_equal(a.responses, b.responses)
    assert np.array_equal(a.offsets, b.offsets)
    assert np.array_equal(a.weights, b.weights)
    assert a.responses.dtype == b.responses.dtype
    assert (a.uids == b.uids).all()
    assert set(a.feature_shards) == set(b.feature_shards)
    for name in a.feature_shards:
        ma, mb = a.feature_shards[name], b.feature_shards[name]
        assert np.array_equal(ma.data, mb.data)
        assert np.array_equal(ma.indices, mb.indices)
        assert np.array_equal(ma.indptr, mb.indptr)
    assert set(a.id_columns) == set(b.id_columns)
    for t in a.id_columns:
        assert np.array_equal(a.id_columns[t].codes, b.id_columns[t].codes)
        assert np.array_equal(a.id_columns[t].vocabulary,
                              b.id_columns[t].vocabulary)


def test_worker_count_invariance_game_dataset(training_file):
    """Datasets from workers in {1, 2, 4} are byte-identical, row order
    included — the core contract of the parallel path."""
    datasets = {
        w: read_game_dataset(training_file, id_types=["userId", "itemId"],
                             ingest_workers=w)[0]
        for w in (1, 2, 4)}
    _assert_datasets_identical(datasets[1], datasets[2])
    _assert_datasets_identical(datasets[1], datasets[4])


def test_worker_count_invariance_labeled_points(training_file):
    mats, ys, uidss = {}, {}, {}
    imap = None
    for w in (1, 2, 4):
        mat, y, off, weights, uids, imap = read_labeled_points(
            training_file, index_map=imap, ingest_workers=w)
        mats[w], ys[w], uidss[w] = mat, y, uids
    for w in (2, 4):
        assert np.array_equal(ys[1], ys[w])
        assert uidss[1] == uidss[w]
        assert np.array_equal(mats[1].data, mats[w].data)
        assert np.array_equal(mats[1].indices, mats[w].indices)
        assert np.array_equal(mats[1].indptr, mats[w].indptr)


def test_multi_file_order_preserved(tmp_path, rng):
    """Shards never cross files and assemble in file order: two files read
    in parallel equal their single-process concatenation."""
    p1, p2 = tmp_path / "a.avro", tmp_path / "b.avro"
    _write_training_file(p1, 1200, rng)
    _write_training_file(p2, 800, rng)
    d1, maps = read_game_dataset([p1, p2], id_types=["userId"],
                                 ingest_workers=1)
    d2, _ = read_game_dataset([p1, p2], id_types=["userId"],
                              feature_shard_maps=maps, ingest_workers=3)
    _assert_datasets_identical(d1, d2)


def test_fallback_without_native_decoder(training_file, monkeypatch):
    """With the C decoder unavailable, a parallel worker request degrades
    gracefully to the pure-python path — same values, no error."""
    native = read_game_dataset(training_file, id_types=["userId"],
                               ingest_workers=2)[0]

    import photon_ml_tpu.native as nat

    monkeypatch.setattr(nat, "_loaded", True)
    monkeypatch.setattr(nat, "_module", None)
    fallback = read_game_dataset(training_file, id_types=["userId"],
                                 ingest_workers=4)[0]
    _assert_datasets_identical(native, fallback)


def test_corrupt_payload_names_shard(tmp_path, rng):
    """Garbage INSIDE a block payload (structurally valid container, so the
    planner scan passes) fails in the worker and surfaces as a clean
    IngestShardError naming the shard — never a hung pool."""
    p = tmp_path / "bad.avro"
    _write_training_file(p, 3000, rng)
    index = scan_container_blocks(p)
    assert len(index.blocks) >= 4

    from photon_ml_tpu.data.avro_reader import build_index_map

    imap = build_index_map(p, ingest_workers=1)  # before corruption
    raw = bytearray(p.read_bytes())
    block = index.blocks[len(index.blocks) // 2]

    def varint_len(off):
        k = 0
        while raw[off + k] & 0x80:
            k += 1
        return k + 1

    payload_start = block.offset + varint_len(block.offset)
    payload_start += varint_len(payload_start)
    # Clobber deflate bytes mid-payload; sizes and sync stay intact.
    for i in range(8):
        raw[payload_start + 4 + i] ^= 0xFF
    p.write_bytes(bytes(raw))

    with pytest.raises(IngestShardError, match="bad.avro"):
        parallel_fast_ingest(
            [str(p)], {"global": imap},
            {"global": imap.intercept_index}, id_types=["userId"],
            workers=2)


def test_truncated_file_clean_error(tmp_path, rng):
    """A truncated container fails the planner scan with an error naming
    the file and offset (before any worker starts)."""
    p = tmp_path / "trunc.avro"
    _write_training_file(p, 2000, rng)
    raw = p.read_bytes()
    p.write_bytes(raw[:len(raw) // 2])
    with pytest.raises(ValueError, match="trunc.avro"):
        read_game_dataset(p, id_types=["userId"], ingest_workers=2)


def test_shard_planner_covers_all_blocks(training_file):
    index = scan_container_blocks(training_file)
    assert index.num_rows == 3000
    for num_shards in (1, 3, 7, 100):
        shards = plan_shards([index], num_shards)
        assert [s.seq for s in shards] == list(range(len(shards)))
        assert sum(s.num_rows for s in shards) == 3000
        assert sum(s.num_blocks for s in shards) == len(index.blocks)
        assert shards[0].offset == index.blocks[0].offset
        # Consecutive coverage: each shard starts at the block after the
        # previous shard's last block.
        starts = [b.offset for b in index.blocks]
        i = 0
        for s in shards:
            assert s.offset == starts[i]
            i += s.num_blocks
        assert i == len(index.blocks)


def test_auto_mode_declines_tiny_inputs(training_file):
    """In auto mode the pool is skipped below MIN_PARALLEL_BYTES (startup
    would dominate); explicit worker counts still parallelize."""
    from photon_ml_tpu.data.avro_reader import build_index_map

    imap = build_index_map(training_file, ingest_workers=1)
    assert parallel_fast_ingest(
        [str(training_file)], {"global": imap},
        {"global": imap.intercept_index}, workers=4, auto=True) is None
    assert parallel_fast_ingest(
        [str(training_file)], {"global": imap},
        {"global": imap.intercept_index}, workers=2, auto=False) is not None


def test_resolve_ingest_workers():
    assert resolve_ingest_workers(1) == 1
    assert resolve_ingest_workers("4") == 4
    assert resolve_ingest_workers("auto") >= 1
    assert resolve_ingest_workers(None) >= 1
    with pytest.raises(ValueError):
        resolve_ingest_workers(0.5)
    with pytest.raises(ValueError):
        resolve_ingest_workers("-2")


def test_chunked_device_put_matches_monolithic(rng):
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.data.device_feed import chunked_device_put

    x = rng.normal(0, 1, (257, 5)).astype(np.float64)
    whole = jnp.asarray(x, jnp.float32)
    chunked = chunked_device_put(x, jnp.float32, chunk_bytes=4096)
    assert chunked.dtype == whole.dtype
    np.testing.assert_array_equal(np.asarray(chunked), np.asarray(whole))

    m = sp.csr_matrix(x)
    from_sparse = chunked_device_put(m, jnp.float32, chunk_bytes=4096)
    np.testing.assert_array_equal(np.asarray(from_sparse),
                                  np.asarray(whole))
    # Single-put path (below the chunk threshold) is equivalent too.
    small = chunked_device_put(x, jnp.float32)
    np.testing.assert_array_equal(np.asarray(small), np.asarray(whole))


def test_overlapped_uploader_concatenates_in_order(rng):
    import jax.numpy as jnp

    from photon_ml_tpu.data.device_feed import OverlappedUploader

    chunks = [rng.normal(0, 1, (n,)).astype(np.float32)
              for n in (100, 37, 256, 1)]
    up = OverlappedUploader(dtype=jnp.float32)
    for c in chunks:
        up.submit(c)
    out = up.collect()
    np.testing.assert_array_equal(np.asarray(out), np.concatenate(chunks))
    assert up.collect() is None


def test_column_consumer_sees_rows_in_order(training_file):
    from photon_ml_tpu.data.avro_reader import build_index_map

    imap = build_index_map(training_file, ingest_workers=1)
    seen = []
    res = parallel_fast_ingest(
        [str(training_file)], {"global": imap},
        {"global": imap.intercept_index}, workers=2,
        column_consumer=lambda seq, lb, ob, wb: seen.append(
            (seq, np.array(lb))))
    assert res is not None
    assert [s for s, _ in seen] == sorted(s for s, _ in seen)
    np.testing.assert_array_equal(
        np.concatenate([a for _, a in seen]), res.labels)
