"""Index map tests (reference: util/PalDBIndexMapTest, DefaultIndexMapTest)."""

import numpy as np
import pytest

from photon_ml_tpu.data.index_map import (
    DELIMITER,
    IdentityIndexMap,
    IndexMap,
    feature_key,
    split_key,
)
from photon_ml_tpu.optimization.config import (
    constraint_arrays,
    parse_constraint_string,
)


def test_feature_key_uses_control_byte_delimiter():
    assert DELIMITER == ""
    # name='ab',term='c' must NOT collide with name='a',term='bc'.
    assert feature_key("ab", "c") != feature_key("a", "bc")
    assert split_key(feature_key("n", "t")) == ("n", "t")
    assert split_key(feature_key("n")) == ("n", "")


def test_round_trip_and_missing_key(tmp_path):
    m = IndexMap.from_name_terms([("b", ""), ("a", "x"), ("a", "")],
                                 add_intercept=True)
    assert len(m) == 4
    assert m.intercept_index == 3  # intercept appended last
    assert m.get_index(feature_key("nope")) == -1
    assert m.get_feature_name(m.get_index(feature_key("a", "x"))) == \
        feature_key("a", "x")
    p = tmp_path / "imap.json"
    m.save(p)
    m2 = IndexMap.load(p)
    assert dict(m2.key_items()) == dict(m.key_items())


def test_identity_index_map():
    m = IdentityIndexMap(5, intercept_last=True)
    assert m.get_index(feature_key("0")) == 0
    assert m.get_index(feature_key("3")) == 3
    assert m.intercept_index == 4


def test_duplicate_indices_rejected():
    with pytest.raises(ValueError):
        IndexMap({"a": 0, "b": 0})


def test_constraint_parsing_with_wildcards():
    m = IndexMap.from_name_terms(
        [("f1", ""), ("f2", "t1"), ("f2", "t2")], add_intercept=True)
    s = ('[{"name": "f2", "term": "*", "lowerBound": -1.0, "upperBound": 1.0},'
         ' {"name": "f1", "term": "", "lowerBound": 0.0}]')
    cmap = parse_constraint_string(s, m)
    assert cmap[m.get_index(feature_key("f2", "t1"))] == (-1.0, 1.0)
    assert cmap[m.get_index(feature_key("f2", "t2"))] == (-1.0, 1.0)
    assert cmap[m.get_index(feature_key("f1"))] == (0.0, float("inf"))

    lo, hi = constraint_arrays(cmap, len(m), intercept_id=m.intercept_index)
    assert lo.shape == (4,)
    assert np.isneginf(lo[m.intercept_index]) and np.isposinf(hi[m.intercept_index])
    assert lo[m.get_index(feature_key("f1"))] == 0.0


def test_constraint_global_wildcard_and_validation():
    m = IndexMap.from_name_terms([("f1", ""), ("f2", "")])
    cmap = parse_constraint_string(
        '[{"name": "*", "term": "*", "lowerBound": -2, "upperBound": 2}]', m)
    assert set(cmap) == {0, 1}
    with pytest.raises(ValueError):
        parse_constraint_string(
            '[{"name": "f1", "term": "", "lowerBound": 3, "upperBound": 1}]', m)
    assert constraint_arrays(None, 3) == (None, None)
