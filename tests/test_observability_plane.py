"""Driver-level integration of the live observability plane
(photon_ml_tpu/cli/obs.py): a --serve run with --obs-port answers
/metrics (validated by this suite's own Prometheus parser), /healthz and
/statusz WHILE running; a forced driver fault leaves a Perfetto-loadable
flight.json whose last events cover the failing stage; the SLO block
lands in metrics.json. Unit-level semantics live in test_exposition.py."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu.cli import game_scoring_driver, game_training_driver

from tests.test_cli_drivers import _train_small_game, _write_sparse_fe_avro
from tests.test_exposition import parse_prometheus


def _scrape_while_alive(out_dir, results):
    """Background scraper: wait for <out_dir>/obs_port, then poll the
    three endpoints until the server goes away, keeping the last
    successful body of each."""
    port_file = out_dir / "obs_port"
    deadline = time.monotonic() + 60
    while not port_file.exists():
        if time.monotonic() > deadline:
            results["error"] = "obs_port file never appeared"
            return
        time.sleep(0.01)
    from photon_ml_tpu.telemetry import read_obs_descriptor
    port = read_obs_descriptor(port_file)["port"]
    results["port"] = port
    while True:
        try:
            for route, key in (("/metrics", "metrics"),
                               ("/healthz", "healthz"),
                               ("/statusz", "statusz"),
                               ("/distz", "distz")):
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{route}", timeout=5)
                assert r.status == 200
                results[key] = r.read().decode()
            results["scrapes"] = results.get("scrapes", 0) + 1
        except (urllib.error.URLError, ConnectionError, OSError):
            return  # server stopped with the driver: done
        time.sleep(0.02)


@pytest.mark.needs_f64
def test_serve_with_obs_port_answers_live(tmp_path, rng):
    """Acceptance: a live --serve --obs-port process answers /metrics in
    valid Prometheus text (our own parser), /healthz, and /statusz —
    scraped WHILE the driver runs, not post-mortem."""
    model_dir, valid = _train_small_game(tmp_path, rng)
    out = tmp_path / "score-serve-obs"
    out.mkdir()
    results = {}
    scraper = threading.Thread(
        target=_scrape_while_alive, args=(out, results), daemon=True)
    scraper.start()
    summary = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(out),
        "--serve", "--request-rows", "7", "--serve-concurrency", "8",
        "--coalesce-ms", "1", "--feeder", "python",
        "--obs-port", "0",
        "--slo", "shed=ratio:serving.frontend.rejected/"
                 "serving.frontend.admitted+serving.frontend.rejected"
                 "<=0.05",
    ])
    scraper.join(timeout=60)
    assert "error" not in results
    assert results.get("scrapes", 0) >= 1, "never scraped the live run"
    # /metrics parsed under the suite's own strict reader
    fams = parse_prometheus(results["metrics"])
    assert "observability_scrapes_total" in fams
    assert json.loads(results["healthz"])["status"] == "ok"
    statusz = json.loads(results["statusz"])
    assert statusz["telemetry_enabled"] is True
    assert "metrics" in statusz and "stage_attribution" in statusz
    assert "shed" in statusz["slo"]
    # the run itself is unchanged by being observed
    assert summary["scoring_path"] == "async-frontend"
    # metrics.json carries the observability + slo blocks
    obs_block = summary["observability"]
    assert obs_block["server"]["port"] == results["port"]
    assert obs_block["server"]["scrapes"] >= results["scrapes"]
    assert obs_block["flight_recorder"]["ring_capacity"] == 4096
    assert summary["slo"]["shed"]["compliant"] is True
    # the frontend's stats ride under /statusz once serving started
    # (best-effort: the scraper may have stopped before _run_serve
    # registered the provider on very fast runs)
    if statusz["status"].get("frontend"):
        fe = statusz["status"]["frontend"]
        assert "pending_by_model" in fe and "cache" in fe


def test_stream_train_distmon_distz_live(tmp_path, rng):
    """Acceptance: /distz serves LIVE label/feature distributions during
    a --stream-train --distmon run (scraped while the driver solves),
    and the data.dist.* headline gauges ride the live /metrics
    exposition via the scrape-hook refresh."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=300, d=40)
    out = tmp_path / "distmon-live"
    out.mkdir()
    results = {}
    scraper = threading.Thread(
        target=_scrape_while_alive, args=(out, results), daemon=True)
    scraper.start()
    summary = game_training_driver.run([
        "--train-input-dirs", str(train),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:15,1e-7,1.0,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--stream-train", "--batch-rows", "64",
        "--hbm-budget", "8K", "--distmon", "--obs-port", "0",
    ])
    scraper.join(timeout=60)
    assert "error" not in results
    assert results.get("scrapes", 0) >= 1
    distz = json.loads(results["distz"])
    assert "training" in distz, sorted(distz)
    tr = distz["training"]
    assert tr["rows"] >= 1  # live mid-run (last scrape sees it full)
    assert tr["columns"]["label"]["quantiles"]["count"] == tr["rows"]
    assert "global" in tr["feature_shards"]
    # headline gauges were refreshed onto the live /metrics exposition
    fams = parse_prometheus(results["metrics"])
    assert "data_dist_rows" in fams
    assert fams["data_dist_rows"]["samples"][0][2] >= 1
    # and the final summary agrees with the plane
    assert summary["data_quality"]["rows"] == 300


def test_serve_distmon_distz_live(tmp_path, rng):
    """Acceptance: /distz serves the live per-model score distribution
    during a --serve --distmon run."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=240, d=30)
    model_out = tmp_path / "model"
    game_training_driver.run([
        "--train-input-dirs", str(train),
        "--output-dir", str(model_out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:15,1e-7,1.0,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--stream-train", "--batch-rows", "64", "--distmon"])
    out = tmp_path / "serve-distz"
    out.mkdir()
    results = {}
    scraper = threading.Thread(
        target=_scrape_while_alive, args=(out, results), daemon=True)
    scraper.start()
    summary = game_scoring_driver.run([
        "--input-dirs", str(train),
        "--game-model-input-dir", str(model_out / "best"),
        "--output-dir", str(out),
        "--serve", "--request-rows", "4", "--serve-concurrency", "8",
        "--distmon", "--obs-port", "0",
    ])
    scraper.join(timeout=60)
    assert "error" not in results
    assert results.get("scrapes", 0) >= 1
    distz = json.loads(results["distz"])
    assert "serving" in distz, sorted(distz)
    mon = distz["serving"]["default"]
    assert mon["scores"]["moments"]["count"] >= 0  # live snapshot
    assert summary["distributions"]["default"]["scores"]["moments"][
        "count"] == 240
    # drift against the embedded reference rode along (same input ->
    # compliant-low PSI)
    assert summary["distributions"]["default"]["drift"]["psi"] < 0.25


def test_driver_fault_dumps_flight_json(tmp_path, rng):
    """Acceptance: a forced fault (corrupt Avro input) produces a
    Perfetto-loadable flight.json whose LAST events cover the failing
    stage — the spans unwound through the fault before the dump."""
    model_dir, _ = _train_small_game(tmp_path, rng)
    bad_in = tmp_path / "bad-input"
    bad_in.mkdir()
    (bad_in / "part-00000.avro").write_bytes(b"this is not avro")
    out = tmp_path / "score-fault"
    with pytest.raises(Exception) as ei:
        game_scoring_driver.run([
            "--input-dirs", str(bad_in),
            "--game-model-input-dir", str(model_dir),
            "--output-dir", str(out),
        ])
    assert not isinstance(ei.value, SystemExit)
    flight = json.loads((out / "flight.json").read_text())
    assert flight["flight"]["reason"] == \
        f"fault:{type(ei.value).__name__}"
    # Perfetto shape: trace events with M/X/C phases + the flight block
    assert {e["ph"] for e in flight["traceEvents"]} <= {"M", "X", "C"}
    names = [e["name"] for e in flight["traceEvents"]
             if e.get("ph") == "X"]
    # the failing stage (ingest reads the corrupt container) and the
    # root driver span both unwound into the ring; driver is LAST
    assert "ingest" in names and names[-1] == "driver"
    assert flight["flight"]["final_metrics"]["counters"] is not None
    assert "ingest" in flight["flight"]["stage_attribution"]


def test_flight_events_zero_disables_recorder(tmp_path, rng):
    model_dir, _ = _train_small_game(tmp_path, rng)
    bad_in = tmp_path / "bad-input"
    bad_in.mkdir()
    (bad_in / "part-00000.avro").write_bytes(b"junk")
    out = tmp_path / "score-norec"
    with pytest.raises(Exception):
        game_scoring_driver.run([
            "--input-dirs", str(bad_in),
            "--game-model-input-dir", str(model_dir),
            "--output-dir", str(out),
            "--flight-events", "0",
        ])
    assert not (out / "flight.json").exists()


def test_stream_train_obs_heartbeat(tmp_path, rng):
    """The training driver's opt-in plane: --stream-train --obs-port 0
    is scrapeable while solving, and the 1 Hz heartbeat block lands in
    metrics.json."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=240, d=40)
    out = tmp_path / "game-out-obs"
    out.mkdir()
    results = {}
    scraper = threading.Thread(
        target=_scrape_while_alive, args=(out, results), daemon=True)
    scraper.start()
    summary = game_training_driver.run([
        "--train-input-dirs", str(train),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:15,1e-7,1.0,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--stream-train", "--batch-rows", "64", "--feeder", "python",
        "--obs-port", "0",
    ])
    scraper.join(timeout=60)
    assert "error" not in results
    assert results.get("scrapes", 0) >= 1
    parse_prometheus(results["metrics"])  # valid exposition, live
    obs_block = summary["observability"]
    assert obs_block["server"]["heartbeat_s"] == 1.0
    assert obs_block["server"]["port"] == results["port"]
    assert summary["stream_train"]["mode"] == "resident-assembled"
    # the run's phases were visible to the plane (the final telemetry
    # block's stage table covers the stream-train pipeline; the fused
    # resident-path solver deliberately has no per-iteration counter)
    stages = summary["telemetry"]["stage_attribution"]
    assert "solve" in stages and "ingest" in stages


def test_stream_train_mf_factor_cache_statusz_provider(tmp_path, rng):
    """The streamed-MF factor cache registers as a live /statusz
    provider: hits/misses/evictions/spill bytes are scrapeable WHILE an
    MF train runs under --hbm-budget (mirroring the fixed-effect
    shard-cache provider)."""
    from tests.test_cli_drivers import _MF_STREAM_BASE, _write_mf_avro

    train = tmp_path / "train"
    _write_mf_avro(train, rng, n=240)
    out = tmp_path / "mf-out-obs"
    out.mkdir()
    results = {}
    scraper = threading.Thread(
        target=_scrape_while_alive, args=(out, results), daemon=True)
    scraper.start()
    summary = game_training_driver.run(
        ["--train-input-dirs", str(train)] + _MF_STREAM_BASE + [
            "--output-dir", str(out),
            "--stream-train", "--batch-rows", "64",
            "--hbm-budget", "64", "--obs-port", "0"])
    scraper.join(timeout=60)
    assert "error" not in results
    assert results.get("scrapes", 0) >= 1
    parse_prometheus(results["metrics"])  # valid exposition, live
    statusz = json.loads(results["statusz"])
    fc = statusz["status"].get("factor_cache")
    assert fc is not None, sorted(statusz["status"])
    for key in ("hits", "misses", "evictions", "spill_bytes_host",
                "resident_shards", "hbm_budget_bytes"):
        assert key in fc, key
    assert fc["hbm_budget_bytes"] == 64
    assert summary["stream_train"]["mode"] == "mf-stream"
    assert summary["stream_train"]["cache"]["evictions"] > 0
    # sweeps landed on the trace tail (one TraceContext per sweep)
    assert summary["observability"]["trace_tail"]["seen"] >= 2


@pytest.mark.needs_f64
def test_scoring_metrics_json_includes_new_frontend_keys(tmp_path, rng):
    """The per-model admission view is part of the stats()/statusz
    schema now — present (empty maps, None quota) even when unused."""
    model_dir, valid = _train_small_game(tmp_path, rng)
    out = tmp_path / "score-serve-schema"
    summary = game_scoring_driver.run([
        "--input-dirs", str(valid),
        "--game-model-input-dir", str(model_dir),
        "--output-dir", str(out),
        "--serve", "--request-rows", "35", "--feeder", "python",
    ])
    fe = summary["frontend"]
    assert fe["max_pending_per_model"] is None
    assert fe["rejected_by_model"] == {}
    assert fe["pending_by_model"] == {"default": 0}
    assert fe["admitted"] == \
        fe["completed"] + fe["failed"] + fe["cancelled"]
    np.testing.assert_equal(fe["failed"], 0)


def _aggregate_while_alive(out_dir, results):
    """Background fleet aggregator (telemetry/federation.py): discover
    the driver's obs_port descriptor, poll /snapshotz, and keep the
    last merged /metrics, /distz and /tracez bodies."""
    from photon_ml_tpu.telemetry.federation import FleetAggregator

    port_file = out_dir / "obs_port"
    deadline = time.monotonic() + 60
    while not port_file.exists():
        if time.monotonic() > deadline:
            results["error"] = "obs_port file never appeared"
            return
        time.sleep(0.01)
    agg = FleetAggregator(peer_dirs=[out_dir], interval_s=0.05)
    agg.start()
    try:
        while True:
            agg.poll_once()
            stale = agg.peer_staleness()
            fresh = [p for p, s in stale.items() if s["has_snapshot"]]
            if fresh and not any(s["last_error"]
                                 for s in stale.values()):
                try:
                    for route, key in (("/metrics", "metrics"),
                                       ("/distz", "distz"),
                                       ("/tracez", "tracez"),
                                       ("/statusz", "statusz")):
                        r = urllib.request.urlopen(
                            f"http://127.0.0.1:{agg.port}{route}",
                            timeout=5)
                        assert r.status == 200
                        results[key] = r.read().decode()
                    results["ready_code"] = urllib.request.urlopen(
                        f"http://127.0.0.1:{agg.port}/readyz",
                        timeout=5).status
                    results["merges"] = results.get("merges", 0) + 1
                except (urllib.error.URLError, ConnectionError,
                        OSError):
                    return
            if stale and all(s["last_error"] for s in stale.values()):
                return  # the driver's plane went away: done
            time.sleep(0.02)
    finally:
        agg.stop()


@pytest.mark.needs_f64
def test_fleet_aggregator_over_live_training_run(tmp_path, rng):
    """Acceptance: a FleetAggregator discovers a LIVE --stream-train
    --distmon --obs-port run via its JSON obs_port descriptor, serves
    merged /metrics (valid Prometheus text carrying the peer's series
    AND the aggregator's fleet.* staleness gauges), merged /distz with
    per-process attribution, and reports ready while the peer is
    fresh."""
    train = tmp_path / "train"
    _write_sparse_fe_avro(train, rng, n=300, d=40)
    out = tmp_path / "fleet-live"
    out.mkdir()
    results = {}
    agg_thread = threading.Thread(
        target=_aggregate_while_alive, args=(out, results), daemon=True)
    agg_thread.start()
    game_training_driver.run([
        "--train-input-dirs", str(train),
        "--output-dir", str(out),
        "--task-type", "LOGISTIC_REGRESSION",
        "--fixed-effect-data-configurations", "fixed:global",
        "--fixed-effect-optimization-configurations",
        "fixed:15,1e-7,1.0,1.0,LBFGS,L2",
        "--updating-sequence", "fixed",
        "--stream-train", "--batch-rows", "32", "--feeder", "python",
        "--distmon", "--obs-port", "0"])
    agg_thread.join(timeout=60)
    assert "error" not in results, results.get("error")
    assert results.get("merges", 0) >= 1
    # Merged /metrics: valid exposition, peer's registry series summed
    # in, and the aggregator's reserved fleet.* namespace present.
    families = parse_prometheus(results["metrics"])
    assert "fleet_peers" in families
    assert "fleet_peers_fresh" in families
    assert any(n.startswith("fleet_peer_training_")
               for n in families), sorted(families)[:10]
    assert any(n.startswith("data_dist_") for n in families)
    # Merged /distz: fleet rollup + per-process breakdown, carrying
    # the training monitor's sketch states.
    distz = json.loads(results["distz"])
    assert "training" in distz["fleet"]
    assert any(k.startswith("columns.label.")
               for k in distz["fleet"]["training"])
    assert len(distz["peers"]) == 1
    (peer_sketches,) = distz["peers"].values()
    assert "training" in peer_sketches
    # Merged /tracez: the peer's tail-sampled solves, tagged with the
    # peer id (per-process attribution).
    tracez = json.loads(results["tracez"])
    assert tracez["seen"] >= 1
    tagged = [t for ring in tracez["traces"].values() for t in ring]
    assert tagged and all("peer" in t for t in tagged)
    # Aggregator readiness: >= 1 fresh peer while the run was live.
    assert results["ready_code"] == 200
    statusz = json.loads(results["statusz"])
    # peer_processes carries the aggregator's own fleet.* pseudo-peer
    # alongside the scraped peers (federation.py SELF_PEER_ID) — the
    # driver must be the only REAL peer.
    (peer_meta,) = (v for v in statusz["peer_processes"].values()
                    if v["role"] != "aggregator")
    assert peer_meta["role"] == "training"
    assert peer_meta["pid"] > 0
