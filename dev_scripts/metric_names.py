#!/usr/bin/env python3
"""Metric-name schema lint: every literal name handed to the telemetry
factories (``counter(...)``/``gauge(...)``/``histogram(...)``) must be
dotted snake_case (docs/OBSERVABILITY.md §Prometheus naming), and a name
must never be registered as two different metric types.

The registry is get-or-create by NAME with no type check across call
sites — ``counter("x")`` in one module and ``gauge("x")`` in another
would silently coexist as two metrics whose exposition families collide
— and the Prometheus mapping (telemetry/exposition.py) sanitizes
characters outside ``[a-zA-Z0-9_:]``, so a camelCase or hyphenated name
would silently diverge from the documented ``dots -> underscores``
mapping dashboards are built against. This gate keeps both invariants
static, like jaxlint keeps the tracing invariants.

Scope and mechanics:

- AST walk of ``photon_ml_tpu/`` + ``bench.py`` (tests are EXEMPT: the
  exposition tests deliberately register schema-violating names to
  exercise escaping).
- A call counts as a registration when it is ``<anything>.counter(...)``
  / ``.gauge(...)`` / ``.histogram(...)`` (the ``telemetry.X`` /
  ``registry().X`` forms) or a bare name imported from
  ``photon_ml_tpu.telemetry``.
- A fully-literal first argument (string constant, or a constant-only
  concatenation) is schema-checked whole:
  ``segment(.segment)*`` with each segment ``[a-z][a-z0-9_]*``.
- A PARTIALLY literal argument (f-string or concatenation with a
  variable — the per-model ``serving.model.<label>.*`` family) has its
  literal fragments checked for illegal characters (uppercase or
  anything outside ``[a-z0-9_.]``); the dynamic parts are runtime
  values the lint cannot see.
- An EXEMPLAR-BEARING histogram (``histogram(..., exemplars=True)`` —
  its buckets carry trace_id exemplars rendered on /metrics,
  docs/OBSERVABILITY.md §Exemplars) must name a latency distribution:
  the literal name must end in ``_seconds`` (exemplars link latency
  buckets to /tracez timelines; a counter-shaped or unitless histogram
  carrying exemplars is a schema smell), and one name must not be
  declared exemplar-bearing at one site and plain at another (the
  registry is get-or-create — whichever call runs first would silently
  win).
- GAUGE-ONLY metric families (docs/OBSERVABILITY.md §Distributions &
  drift): names under ``data.dist.`` (distribution-sketch headline
  values, refreshed whole by scrape hooks) and names containing
  ``score_drift_`` (the ``serving.model.<label>.score_drift_psi``/
  ``_ks`` drift scores, COMPUTED on scrape) are instantaneous readings
  by construction — a counter or histogram under either family would
  break the ``--slo`` value-objective contract and every dashboard
  rate() built on the family. Checked on full literals AND on literal
  fragments of partially-dynamic names (the per-model f-string form).
- The ``fleet.`` prefix is RESERVED for the fleet aggregator
  (telemetry/federation.py): a peer process emitting ``fleet.*`` would
  collide with the aggregator's synthesized series on the merged
  /metrics and break per-process attribution — no file other than
  federation.py may register a name (or literal fragment) starting
  ``fleet.`` (docs/OBSERVABILITY.md §Federation).
- Every GAUGE family must carry a DECLARED merge policy in
  federation.py's ``GAUGE_MERGE_POLICIES`` (exact name, ``prefix.`` or
  ``.suffix`` entry): gauges — unlike counters and histograms — have no
  single correct cross-process merge, and the runtime default of
  ``last`` silently picks "newest snapshot wins" for an undeclared
  family. A new gauge must state whether it sums (bytes held), maxes
  (uptime, burn rates) or follows the newest writer. Full literals must
  resolve against the declared table; partially-dynamic names need at
  least one literal fragment covered by an entry. Skipped entirely
  when the tree has no ``photon_ml_tpu/telemetry/federation.py`` (TP/FP
  tmp-tree tests supply their own).

Exit 0 = clean. Run via tests.sh or directly:
    python dev_scripts/metric_names.py [--root DIR] [paths...]
"""

from __future__ import annotations

import argparse
import ast
import re
import sys
from pathlib import Path

FACTORIES = ("counter", "gauge", "histogram")
DEFAULT_PATHS = ["photon_ml_tpu", "bench.py"]

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)*$")
_FRAGMENT_BAD_RE = re.compile(r"[^a-z0-9_.]")

#: (trigger, match) -> gauge-only family. ``prefix`` triggers on a name
#: (or fragment) starting with the string; ``contains`` anywhere in it.
_GAUGE_ONLY_FAMILIES = (
    ("prefix", "data.dist.", "the data.dist.* distribution family"),
    ("contains", "score_drift_",
     "the serving.model.<label>.score_drift_* drift family"),
)

#: prefix-anchored COUNTER families (the inverse rule): under the
#: prefix, registrations must be counters — the family counts wire
#: events (requests, bytes, typed errors) and dashboards rate() the
#: whole namespace — except gauges whose name ends with an allowlisted
#: instantaneous-reading suffix. Histograms are never allowed (a wire
#: latency distribution belongs under serving.frontend.*, where the
#: SLO thresholds point). Prefix-anchored on fragments like the
#: gauge-only prefix families (the serving.net.errors.<kind> f-string
#: form starts with the literal prefix).
_COUNTER_FAMILIES = (
    ("serving.net.", ("open_connections",),
     "the serving.net.* wire-event family"),
)


def _counter_family_violation(text: str, kind: str):
    """The counter-family rule broken by ``text`` (a full literal name
    or the leading fragment of a partially-dynamic one) under ``kind``,
    if any: returns the family label."""
    for prefix, gauge_suffixes, label in _COUNTER_FAMILIES:
        if not text.startswith(prefix):
            continue
        if kind == "counter":
            return None
        if kind == "gauge" and text.endswith(tuple(gauge_suffixes)):
            return None
        return label
    return None


def _gauge_only_family(text: str, is_fragment: bool):
    """The gauge-only family ``text`` (a full literal name, or one
    literal fragment of a partially-dynamic name) belongs to, if any.
    Prefix families stay prefix-anchored even on fragments (an
    f-string in the family starts with the literal prefix, e.g.
    f"data.dist.{col}") — a fragment merely CONTAINING the prefix
    mid-name (".metadata.dist.errors") is a different namespace."""
    for mode, needle, label in _GAUGE_ONLY_FAMILIES:
        if mode == "prefix":
            hit = text.startswith(needle)
        else:
            hit = needle in text
        if hit:
            return label
    return None


#: Path (relative parts) of the one module allowed to emit ``fleet.*``.
_FEDERATION_PARTS = ("telemetry", "federation.py")


def _is_federation_file(path: Path) -> bool:
    return tuple(path.parts[-2:]) == _FEDERATION_PARTS


def load_gauge_policies(root: Path):
    """Parse ``GAUGE_MERGE_POLICIES`` (a pure dict literal) out of the
    tree's federation module without importing it. Returns the dict, or
    None when the module (or the table) is absent — the gauge-policy
    rule is then skipped, which lets the TP/FP tmp-tree tests declare
    their own minimal table."""
    fed = root / "photon_ml_tpu" / "telemetry" / "federation.py"
    if not fed.is_file():
        return None
    try:
        tree = ast.parse(fed.read_text(encoding="utf-8"),
                         filename=str(fed))
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # NAME: Dict[...] = {...}
            targets = [node.target]
        else:
            continue
        for tgt in targets:
            if (isinstance(tgt, ast.Name)
                    and tgt.id == "GAUGE_MERGE_POLICIES"
                    and isinstance(node.value, ast.Dict)):
                out = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and isinstance(v, ast.Constant)):
                        out[k.value] = v.value
                return out
    return None


def _policy_covers_name(name: str, policies: dict) -> bool:
    """A FULL literal gauge name resolves to a declared policy entry
    (exact > ``.suffix`` endswith > ``prefix.`` startswith — the same
    precedence the runtime resolver uses)."""
    if name in policies:
        return True
    for key in policies:
        if key.startswith(".") and name.endswith(key):
            return True
        if key.endswith(".") and name.startswith(key):
            return True
    return False


def _policy_covers_fragment(frag: str, policies: dict) -> bool:
    """One literal fragment of a partially-dynamic gauge name is
    covered: it matches an exact entry, ends with a ``.suffix`` entry's
    text (dot optional — ``pre + "burn_rate"`` fragments carry no
    leading dot), or overlaps a ``prefix.`` entry in either
    direction."""
    if frag in policies:
        return True
    for key in policies:
        if key.startswith(".") and frag.endswith(key[1:]):
            return True
        if key.endswith(".") and (frag.startswith(key)
                                  or key.startswith(frag)):
            return True
    return False


def _telemetry_bare_names(tree: ast.AST) -> set:
    """Factory names imported directly from the telemetry package
    (``from photon_ml_tpu.telemetry import counter``)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.startswith("photon_ml_tpu.telemetry"):
            for a in node.names:
                if a.name in FACTORIES:
                    out.add(a.asname or a.name)
    return out


def _literal_parts(node):
    """(fragments, fully_literal) for a metric-name argument: the string
    fragments statically present, and whether they cover the WHOLE
    name. Handles plain constants, ``a + b`` concatenation chains, and
    f-strings; anything else contributes an opaque dynamic part."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, str):
            return [node.value], True
        return [], False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        lf, lfull = _literal_parts(node.left)
        rf, rfull = _literal_parts(node.right)
        return lf + rf, lfull and rfull
    if isinstance(node, ast.JoinedStr):
        frags = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                frags.append(v.value)
        return frags, False
    return [], False


def _exemplars_kwarg(node: ast.Call):
    """True/False when the call passes a literal ``exemplars=`` keyword,
    None when absent or non-literal."""
    for kw in node.keywords:
        if kw.arg == "exemplars" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return None


def check_file(path: Path, src: str, registrations: dict,
               gauge_policies: dict = None) -> list:
    """Violations in one file; literal registrations accumulate into
    ``registrations`` (name -> {kind: first location}, with histogram
    kinds split into ``histogram``/``histogram_exemplars`` so an
    exemplar-bearing and a plain declaration of one name conflict) for
    the cross-file conflicting-type check."""
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(path, e.lineno or 0, "syntax",
                 f"does not parse: {e.msg}")]
    bare = _telemetry_bare_names(tree)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in FACTORIES:
            kind = fn.attr
        elif isinstance(fn, ast.Name) and fn.id in bare:
            kind = fn.id
        else:
            continue
        exemplars = (_exemplars_kwarg(node) if kind == "histogram"
                     else None)
        frags, full = _literal_parts(node.args[0])
        if not frags:
            continue  # fully dynamic: runtime's problem
        if full:
            name = "".join(frags)
            if not _NAME_RE.match(name):
                out.append((path, node.lineno, "metric-name-schema",
                            f"{kind}({name!r}): metric names are dotted "
                            "snake_case — segment(.segment)*, each "
                            "[a-z][a-z0-9_]* (docs/OBSERVABILITY.md)"))
            else:
                if exemplars and not name.endswith("_seconds"):
                    out.append((
                        path, node.lineno, "exemplar-histogram-name",
                        f"histogram({name!r}, exemplars=True): exemplar-"
                        "bearing histograms carry trace_id latency "
                        "exemplars and must end in '_seconds' "
                        "(docs/OBSERVABILITY.md §Exemplars)"))
                family = _gauge_only_family(name, is_fragment=False)
                if family is not None and kind != "gauge":
                    out.append((
                        path, node.lineno, "gauge-only-family",
                        f"{kind}({name!r}): {family} is gauge-only — "
                        "distribution/drift values are instantaneous "
                        "readings refreshed on scrape "
                        "(docs/OBSERVABILITY.md §Distributions & "
                        "drift)"))
                cfam = _counter_family_violation(name, kind)
                if cfam is not None:
                    out.append((
                        path, node.lineno, "counter-family",
                        f"{kind}({name!r}): {cfam} is counter-only "
                        "(gauges only for allowlisted instantaneous "
                        "readings, histograms never — wire latency "
                        "belongs under serving.frontend.*) "
                        "(docs/OBSERVABILITY.md §Network front door)"))
                if (name.startswith("fleet.")
                        and not _is_federation_file(path)):
                    out.append((
                        path, node.lineno, "fleet-prefix-reserved",
                        f"{kind}({name!r}): the fleet.* prefix is "
                        "reserved for the aggregator "
                        "(telemetry/federation.py) — a peer emitting "
                        "it would collide with the merged plane "
                        "(docs/OBSERVABILITY.md §Federation)"))
                if (kind == "gauge" and gauge_policies is not None
                        and not _policy_covers_name(
                            name, gauge_policies)):
                    out.append((
                        path, node.lineno, "gauge-merge-policy",
                        f"gauge({name!r}) has no declared merge policy "
                        "in GAUGE_MERGE_POLICIES "
                        "(telemetry/federation.py) — the fleet merge "
                        "would silently default to 'last' (newest "
                        "snapshot wins); declare sum/max/last for the "
                        "family (docs/OBSERVABILITY.md §Federation)"))
                prev = registrations.setdefault(name, {})
                prev.setdefault(kind, (path, node.lineno))
                if exemplars is not None:
                    # Marker entries (filtered out of the type check):
                    # an explicit exemplars=True at one site and
                    # exemplars=False at another disagree about one
                    # get-or-create name; kwarg-less reads stay exempt.
                    prev.setdefault(f"exemplars_{exemplars}".lower(),
                                    (path, node.lineno))
        else:
            for frag in frags:
                m = _FRAGMENT_BAD_RE.search(frag)
                if m:
                    out.append((
                        path, node.lineno, "metric-name-schema",
                        f"{kind}(...{frag!r}...): literal fragment "
                        f"contains {m.group(0)!r} — metric names are "
                        "lowercase [a-z0-9_.] only"))
                    break
            for frag in frags:
                family = _gauge_only_family(frag, is_fragment=True)
                if family is not None and kind != "gauge":
                    out.append((
                        path, node.lineno, "gauge-only-family",
                        f"{kind}(...{frag!r}...): {family} is "
                        "gauge-only — distribution/drift values are "
                        "instantaneous readings refreshed on scrape "
                        "(docs/OBSERVABILITY.md §Distributions & "
                        "drift)"))
                    break
            for frag in frags:
                cfam = _counter_family_violation(frag, kind)
                if cfam is not None:
                    out.append((
                        path, node.lineno, "counter-family",
                        f"{kind}(...{frag!r}...): {cfam} is "
                        "counter-only (gauges only for allowlisted "
                        "instantaneous readings, histograms never) "
                        "(docs/OBSERVABILITY.md §Network front door)"))
                    break
            for frag in frags:
                if (frag.startswith("fleet.")
                        and not _is_federation_file(path)):
                    out.append((
                        path, node.lineno, "fleet-prefix-reserved",
                        f"{kind}(...{frag!r}...): the fleet.* prefix "
                        "is reserved for the aggregator "
                        "(telemetry/federation.py) "
                        "(docs/OBSERVABILITY.md §Federation)"))
                    break
            if (kind == "gauge" and gauge_policies is not None
                    and not any(_policy_covers_fragment(
                        f, gauge_policies) for f in frags)):
                out.append((
                    path, node.lineno, "gauge-merge-policy",
                    f"gauge(...{frags[0]!r}...) has no literal "
                    "fragment covered by GAUGE_MERGE_POLICIES "
                    "(telemetry/federation.py) — the fleet merge "
                    "would silently default to 'last'; declare "
                    "sum/max/last for the family "
                    "(docs/OBSERVABILITY.md §Federation)"))
    return out


_MARKER_KINDS = ("exemplars_true", "exemplars_false")


def conflicting_types(registrations: dict) -> list:
    out = []
    for name, kinds in sorted(registrations.items()):
        real = {k: v for k, v in kinds.items()
                if k not in _MARKER_KINDS}
        if len(real) > 1:
            where = ", ".join(
                f"{kind} at {p}:{ln}"
                for kind, (p, ln) in sorted(real.items()))
            out.append((Path("-"), 0, "metric-type-conflict",
                        f"{name!r} registered as multiple metric types: "
                        f"{where}"))
        if all(m in kinds for m in _MARKER_KINDS):
            where = ", ".join(
                f"exemplars={m.rsplit('_', 1)[1]} at {p}:{ln}"
                for m, (p, ln) in sorted(kinds.items())
                if m in _MARKER_KINDS)
            out.append((Path("-"), 0, "exemplar-declaration-conflict",
                        f"{name!r} declared both exemplar-bearing and "
                        f"plain ({where}) — the registry is "
                        "get-or-create, whichever runs first wins "
                        "silently"))
    return out


def iter_py_files(root: Path, paths):
    for raw in paths:
        p = root / raw
        if p.is_file():
            yield p
        else:
            yield from sorted(p.rglob("*.py"))


def main(argv) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--root", default=".",
                    help="tree root (for tests against tmp trees)")
    args = ap.parse_args(argv)
    root = Path(args.root)
    paths = args.paths or DEFAULT_PATHS
    registrations: dict = {}
    violations = []
    gauge_policies = load_gauge_policies(root)
    for f in iter_py_files(root, paths):
        violations.extend(
            check_file(f, f.read_text(encoding="utf-8"), registrations,
                       gauge_policies=gauge_policies))
    violations.extend(conflicting_types(registrations))
    for path, lineno, rule, msg in violations:
        print(f"{path}:{lineno}: [{rule}] {msg}")
    if violations:
        print(f"{len(violations)} metric-name violation(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
