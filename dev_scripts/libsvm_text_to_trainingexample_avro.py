#!/usr/bin/env python3
"""Convert a LibSVM text file into TrainingExampleAvro records.

Counterpart of the reference's dev script
(dev-scripts/libsvm_text_to_trainingexample_avro.py): each feature index
becomes the feature ``name``; ``term`` is empty. Classification labels
-1/+1 map to 0/1 unless --regression is given.

Usage:
  python dev_scripts/libsvm_text_to_trainingexample_avro.py \
      INPUT.libsvm OUTPUT_DIR [--regression] [--zero-based]

Writes OUTPUT_DIR/part-00000.avro readable by the GLM/GAME drivers
(--format AVRO). No external Avro dependency — uses the bundled pure-python
container codec (photon_ml_tpu/io/avro_codec.py).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from photon_ml_tpu.io import schemas  # noqa: E402
from photon_ml_tpu.io.avro_codec import write_container  # noqa: E402


def convert(input_path: Path, output_dir: Path, regression: bool,
            zero_based: bool) -> int:
    records = []
    with open(input_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            try:
                label = float(parts[0])
                feats = []
                for tok in parts[1:]:
                    idx_s, val_s = tok.split(":", 1)
                    idx = int(idx_s) - (0 if zero_based else 1)
                    feats.append({"name": str(idx), "term": None,
                                  "value": float(val_s)})
            except (ValueError, IndexError) as e:
                raise SystemExit(
                    f"{input_path}:{lineno}: malformed line ({e})")
            if not regression:
                label = 1.0 if label > 0 else 0.0
            records.append({
                "uid": str(lineno), "label": label, "features": feats,
                "weight": None, "offset": None, "metadataMap": None,
            })
    output_dir.mkdir(parents=True, exist_ok=True)
    write_container(output_dir / "part-00000.avro",
                    schemas.TRAINING_EXAMPLE, records)
    return len(records)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("input", type=Path)
    p.add_argument("output_dir", type=Path)
    p.add_argument("-r", "--regression", action="store_true",
                   help="keep raw labels (no -1/+1 -> 0/1 mapping)")
    p.add_argument("--zero-based", action="store_true",
                   help="feature indices in the input start at 0, not 1")
    args = p.parse_args(argv)
    n = convert(args.input, args.output_dir, args.regression,
                args.zero_based)
    print(f"wrote {n} records to {args.output_dir}/part-00000.avro")


if __name__ == "__main__":
    main()
