"""Chip validation: all three Pallas entity-solver modes compile and run
on real TPU, with a timed bucket solve each. Run after any kernel change
(and after a tunnel outage) before trusting TPU results:
    python dev_scripts/chip_validation.py
"""
def main():
    import time
    import numpy as np, jax, jax.numpy as jnp

    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.pallas_entity_solver import pallas_entity_lbfgs
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(3)
    e, r, d = 5000, 40, 25
    x = rng.normal(0, 1, (e, r, d)).astype(np.float32); x[:, :, 0] = 1.0
    wt = rng.normal(0, 0.4, (e, d))
    z = np.einsum("erd,ed->er", x, wt)
    y = (rng.random((e, r)) < 1/(1+np.exp(-z))).astype(np.float32)
    yp = rng.poisson(2.0, (e, r)).astype(np.float32)
    off = np.zeros((e, r), np.float32); w = np.ones((e, r), np.float32)

    def sync(v): np.asarray(jax.device_get(jax.tree.leaves(v)[0].ravel()[0]))

    def timed(fn, reps=8):
        out = fn(); sync(out)
        t0 = time.perf_counter()
        for _ in range(reps): out = fn()
        sync(out)
        return (time.perf_counter() - t0) / reps * 1e3, out

    log_loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    poi_loss = loss_for_task(TaskType.POISSON_REGRESSION)
    xa, ya, ypa = jnp.asarray(x), jnp.asarray(y), jnp.asarray(yp)
    offa, wa = jnp.asarray(off), jnp.asarray(w)
    c0 = jnp.zeros((e, d), np.float32)

    for mode, loss, yy, l1, l2 in [
        ("lbfgs", log_loss, ya, 0.0, 1.0),
        ("owlqn", log_loss, ya, 0.5, 0.5),
        ("tron", poi_loss, ypa, 0.0, 1.0),
    ]:
        ms, res = timed(lambda: pallas_entity_lbfgs(
            loss, xa, yy, offa, wa, c0, l2, l1,
            max_iter=15, tol=1e-6, mode=mode))
        xs = np.asarray(jax.device_get(res.x))
        assert np.isfinite(xs).all(), mode
        print(f"{mode:6s}: {ms:7.2f} ms  mean_iters="
              f"{float(np.asarray(res.iterations).mean()):.1f}  finite OK",
              flush=True)
    print("ALL MODES COMPILE+RUN ON CHIP", flush=True)


if __name__ == "__main__":
    main()
