"""Chip validation: all ten Pallas entity-solver variants (3 modes x
normalization/bounds folds) run and are timed on real TPU, then the
gather-wall candidates. Run after any kernel change (and after a tunnel
outage) before trusting TPU results:
    python dev_scripts/chip_validation.py
Compile-only certification without a chip: dev_scripts/mosaic_aot_check.py
"""
def main():
    import time
    import numpy as np, jax, jax.numpy as jnp

    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.pallas_entity_solver import pallas_entity_lbfgs
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(3)
    e, r, d = 5000, 40, 25
    x = rng.normal(0, 1, (e, r, d)).astype(np.float32); x[:, :, 0] = 1.0
    wt = rng.normal(0, 0.4, (e, d))
    z = np.einsum("erd,ed->er", x, wt)
    y = (rng.random((e, r)) < 1/(1+np.exp(-z))).astype(np.float32)
    yp = rng.poisson(2.0, (e, r)).astype(np.float32)
    off = np.zeros((e, r), np.float32); w = np.ones((e, r), np.float32)

    def sync(v): np.asarray(jax.device_get(jax.tree.leaves(v)[0].ravel()[0]))

    def timed(fn, reps=8):
        out = fn(); sync(out)
        t0 = time.perf_counter()
        for _ in range(reps): out = fn()
        sync(out)
        return (time.perf_counter() - t0) / reps * 1e3, out

    log_loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    poi_loss = loss_for_task(TaskType.POISSON_REGRESSION)
    xa, ya, ypa = jnp.asarray(x), jnp.asarray(y), jnp.asarray(yp)
    offa, wa = jnp.asarray(off), jnp.asarray(w)
    c0 = jnp.zeros((e, d), np.float32)

    # Per-entity normalization arrays (STANDARDIZATION-like) and box
    # bounds — the round-4 kernel folds; each variant must COMPILE on
    # real Mosaic (interpret-mode parity does not prove that).
    fac = np.tile(1.0 / np.maximum(x.std(axis=(0, 1)), 0.2), (e, 1))
    fac[:, 0] = 1.0
    shf = np.tile(x.mean(axis=(0, 1)), (e, 1))
    shf[:, 0] = 0.0
    faca = jnp.asarray(fac, np.float32)
    shfa = jnp.asarray(shf, np.float32)
    lba = jnp.full((e, d), -0.3, np.float32)
    uba = jnp.full((e, d), 0.3, np.float32)

    for name, mode, loss, yy, l1, l2, kw in [
        ("lbfgs", "lbfgs", log_loss, ya, 0.0, 1.0, {}),
        ("owlqn", "owlqn", log_loss, ya, 0.5, 0.5, {}),
        ("tron", "tron", poi_loss, ypa, 0.0, 1.0, {}),
        ("lbfgs+norm", "lbfgs", log_loss, ya, 0.0, 1.0,
         dict(factors=faca, shifts=shfa)),
        ("lbfgs+bounds", "lbfgs", log_loss, ya, 0.0, 1.0,
         dict(lower=lba, upper=uba)),
        ("lbfgs+norm+bounds", "lbfgs", log_loss, ya, 0.0, 1.0,
         dict(factors=faca, shifts=shfa, lower=lba, upper=uba)),
        ("owlqn+norm", "owlqn", log_loss, ya, 0.5, 0.5,
         dict(factors=faca, shifts=shfa)),
        ("tron+norm", "tron", poi_loss, ypa, 0.0, 1.0,
         dict(factors=faca, shifts=shfa)),
        ("tron+bounds", "tron", poi_loss, ypa, 0.0, 1.0,
         dict(lower=lba, upper=uba)),
        ("tron+norm+bounds", "tron", poi_loss, ypa, 0.0, 1.0,
         dict(factors=faca, shifts=shfa, lower=lba, upper=uba)),
    ]:
        ms, res = timed(lambda: pallas_entity_lbfgs(
            loss, xa, yy, offa, wa, c0, l2, l1,
            max_iter=15, tol=1e-6, mode=mode, **kw))
        xs = np.asarray(jax.device_get(res.x))
        assert np.isfinite(xs).all(), name
        print(f"{name:18s}: {ms:7.2f} ms  mean_iters="
              f"{float(np.asarray(res.iterations).mean()):.1f}  finite OK",
              flush=True)
    print("ALL KERNEL VARIANTS COMPILE+RUN ON CHIP", flush=True)

    # Sparse gather candidates (docs/SCALE.md wall): measured rates.
    import subprocess
    import sys
    from pathlib import Path

    subprocess.run(
        [sys.executable,
         str(Path(__file__).with_name("gather_experiments.py"))],
        check=False)


if __name__ == "__main__":
    main()
