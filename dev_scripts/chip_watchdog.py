"""TPU tunnel watchdog: probe until the chip returns, then capture
EVERYTHING (VERDICT r4 next-round item 1).

Rounds 3 and 4 produced zero driver-captured chip numbers because the
remote-TPU tunnel was wedged the whole round while perf features piled up
unproven. This script makes the measurement unmissable: it probes the TPU
in a killable subprocess (backend init itself can hang on a dead tunnel —
see bench.py's probe) every --interval seconds, appends every probe to a
JSONL log, and on the FIRST success runs the full capture pipeline:

  1. dev_scripts/chip_validation.py  — all kernel variants must COMPILE on
     real Mosaic (interpret parity does not prove that) + the four
     gather-wall candidates (docs/SCALE.md).
  2. bench.py                        — full artifact (BENCH_full.json) incl.
     bf16, kernel OWL-QN/TRON, norm/bounds GLMix, game_full_phase_ms,
     ingest + scoring extras, scale extras.

Outputs are timestamped into --out-dir (default: repo root):
  CHIP_PROBE_LOG.jsonl              — one line per probe / pipeline step
  CHIP_VALIDATION_<ts>.log          — chip_validation stdout+stderr
  BENCH_chip_<ts>.json              — copy of BENCH_full.json from the run
  BENCH_chip_<ts>.log               — bench stdout+stderr

Usage:
  python dev_scripts/chip_watchdog.py --once        # single probe, exit 0/1
  python dev_scripts/chip_watchdog.py               # daemon until capture
  python dev_scripts/chip_watchdog.py --interval 600 --max-hours 11
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE_CODE = ("import jax; assert any(d.platform == 'tpu' "
              "for d in jax.devices()), 'no TPU device'")


def _ts() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _log(path: str, **fields) -> None:
    fields.setdefault("ts", _ts())
    with open(path, "a") as f:
        f.write(json.dumps(fields) + "\n")
    print(json.dumps(fields), flush=True)


def probe(timeout: float) -> tuple[bool, str]:
    """True iff a TPU device enumerates within ``timeout`` seconds. Runs in
    a subprocess because a wedged tunnel hangs backend INIT itself."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        subprocess.run([sys.executable, "-c", PROBE_CODE],
                       capture_output=True, text=True, timeout=timeout,
                       check=True, env=env)
        return True, "tpu device enumerated"
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout:.0f}s (tunnel wedged)"
    except subprocess.CalledProcessError as e:
        tail = (e.stderr or "").strip().splitlines()
        return False, (tail[-1][:200] if tail else f"exit {e.returncode}")
    except Exception as e:  # noqa: BLE001
        return False, f"{type(e).__name__}: {e}"


def _run_step(name: str, cmd: list, log_path: str, out_file: str,
              timeout: float) -> bool:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # dev_scripts/* import photon_ml_tpu from the repo root; python adds
    # the SCRIPT's dir (not cwd) to sys.path, so the repo must be on
    # PYTHONPATH — alongside whatever the environment already needs
    # there (e.g. the axon TPU plugin's site dir).
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    t0 = time.perf_counter()
    try:
        with open(out_file, "w") as f:
            proc = subprocess.run(cmd, stdout=f, stderr=subprocess.STDOUT,
                                  timeout=timeout, env=env, cwd=REPO)
        ok = proc.returncode == 0
        _log(log_path, event=f"capture:{name}", ok=ok,
             returncode=proc.returncode,
             seconds=round(time.perf_counter() - t0, 1), output=out_file)
        return ok
    except subprocess.TimeoutExpired:
        _log(log_path, event=f"capture:{name}", ok=False,
             error=f"timed out after {timeout:.0f}s", output=out_file)
        return False
    except Exception as e:  # noqa: BLE001
        _log(log_path, event=f"capture:{name}", ok=False,
             error=f"{type(e).__name__}: {e}")
        return False


def capture(out_dir: str, log_path: str) -> bool:
    """Run the full on-chip pipeline; True iff every step succeeded."""
    stamp = _ts().replace(":", "")
    ok_val = _run_step(
        "chip_validation",
        [sys.executable, os.path.join(REPO, "dev_scripts",
                                      "chip_validation.py")],
        log_path, os.path.join(out_dir, f"CHIP_VALIDATION_{stamp}.log"),
        timeout=3600)
    ok_bench = _run_step(
        "bench", [sys.executable, os.path.join(REPO, "bench.py")],
        log_path, os.path.join(out_dir, f"BENCH_chip_{stamp}.log"),
        timeout=7200)
    full = os.path.join(REPO, "BENCH_full.json")
    if ok_bench and os.path.exists(full):
        shutil.copy(full, os.path.join(out_dir, f"BENCH_chip_{stamp}.json"))
    # Best-effort extras LAST (don't gate the capture verdict, and must
    # not eat a short tunnel window before the primary artifacts): the
    # sort/scan/scatter primitive rates that decide the sort-permutation
    # alternative to the random-access wall, then the gather block-width
    # sweep (docs/SCALE.md §Attacking the gather wall). Skipped when
    # both primary steps failed — the tunnel is gone and each extra
    # would burn its full timeout on a dead backend.
    if ok_val or ok_bench:
        _run_step(
            "sort_primitives",
            [sys.executable,
             os.path.join(REPO, "dev_scripts", "sort_primitives.py")],
            log_path, os.path.join(out_dir, f"SORT_PRIMS_{stamp}.log"),
            timeout=1800)
        _run_step(
            "gather_sweep",
            [sys.executable,
             os.path.join(REPO, "dev_scripts", "gather_experiments.py"),
             "--sweep"],
            log_path, os.path.join(out_dir, f"GATHER_SWEEP_{stamp}.log"),
            timeout=1800)
    else:
        _log(log_path, event="capture:extras_skipped",
             detail="both primary steps failed; tunnel presumed gone")
    _log(log_path, event="capture:done", ok=ok_val and ok_bench)
    return ok_val and ok_bench


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--once", action="store_true",
                    help="probe once, log, exit 0 (up) / 1 (down); no capture")
    ap.add_argument("--interval", type=float, default=900,
                    help="seconds between probes (default 900)")
    ap.add_argument("--probe-timeout", type=float, default=120)
    ap.add_argument("--max-hours", type=float, default=12,
                    help="give up after this long (default 12h)")
    ap.add_argument("--out-dir", default=REPO)
    ap.add_argument("--log", default=None,
                    help="probe log path (default <out-dir>/CHIP_PROBE_LOG"
                         ".jsonl)")
    args = ap.parse_args()
    log_path = args.log or os.path.join(args.out_dir, "CHIP_PROBE_LOG.jsonl")

    if args.once:
        ok, detail = probe(args.probe_timeout)
        _log(log_path, event="probe", ok=ok, detail=detail)
        return 0 if ok else 1

    deadline = time.monotonic() + args.max_hours * 3600
    while time.monotonic() < deadline:
        ok, detail = probe(args.probe_timeout)
        _log(log_path, event="probe", ok=ok, detail=detail)
        if ok:
            return 0 if capture(args.out_dir, log_path) else 2
        time.sleep(max(0.0, min(args.interval,
                                deadline - time.monotonic())))
    _log(log_path, event="gave_up",
         detail=f"tunnel never opened in {args.max_hours:g}h")
    return 1


if __name__ == "__main__":
    sys.exit(main())
