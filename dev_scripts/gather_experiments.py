#!/usr/bin/env python3
"""Sparse gather-wall experiments (VERDICT r4 item 3).

The d=2M sparse fixed-effect iteration is gather-bound: XLA random
access runs at a FLAT ~148M lookups/s on v5e (docs/SCALE.md), ~0.07% of
HBM bandwidth, making the sparse path ~440x slower per iteration than
the dense one. Before accepting that wall, this script measures every
alternative implementation of the core primitive

    out[i] = w[idx[i]]   (w: f32[d] table, idx: i32[m], m ~ 12M, d ~ 2M)

on the current backend and prints one JSON line per candidate:

  xla_gather          baseline w[idx] (the 148M/s wall)
  xla_onehot_scan     indices pre-grouped into 2048-wide column blocks;
                      per block, a fused iota-compare one-hot (bf16)
                      contracted against the block's w slice on the MXU.
                      Arithmetic bound: 197e12 MAC/s / 2048 ≈ 48G
                      lookups/s IF XLA fuses the one-hot into the dot
                      without materializing it in HBM.
  pallas_onehot       the same contraction written explicitly as a
                      Pallas kernel (one-hot built in VREGs, jnp.dot on
                      the MXU, f32 accumulation).
  pallas_residue_gather  Pallas kernel holding the whole table in VMEM
                      as [d/128, 128] and issuing LANE-LOCAL
                      dynamic_gathers over residue-class-packed indices
                      (lane l gathers only elements with j%128 == l) —
                      the only arbitrary-gather formulation Mosaic's
                      gather lowering supports; a flat table[idx]
                      raises 'Only 2D gather is supported'.

Run on a real chip:  python dev_scripts/gather_experiments.py
CPU correctness check (tiny shapes + interpret mode):
                     python dev_scripts/gather_experiments.py --check
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

BLOCK = 2048


def _prep_blocks(idx: np.ndarray, d: int, block: int = BLOCK):
    """Group indices by `block`-wide column block, padded per block to
    the max per-block count (value 0 -> gathers w[block_start], masked
    by weight 0). Returns (block_local i32[kb, e], mask f32[kb, e],
    perm i32[m] mapping packed order back to original order)."""
    kb = -(-d // block)
    owner = idx // block
    order = np.argsort(owner, kind="stable")
    counts = np.bincount(owner, minlength=kb)
    e = max(1, int(counts.max()))
    local = np.zeros((kb, e), np.int32)
    mask = np.zeros((kb, e), np.float32)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(idx)) - np.repeat(starts, counts)
    local[owner[order], pos] = (idx[order] - owner[order] * block)
    mask[owner[order], pos] = 1.0
    packed_of = (owner[order] * e + pos)  # position in [kb*e] layout
    slot = np.empty(len(idx), np.int64)
    slot[order] = packed_of
    return local, mask, slot


def make_xla_gather(w, idx):
    """Returns (jitted f, args). Timed over rolled index variants."""
    import jax

    @jax.jit
    def f(w, idx):
        return w[idx]

    return f, (w, idx)


def make_xla_onehot_scan(w, local, mask, block: int = BLOCK):
    """Returns (jitted f, args). Timed over rolled (local, mask)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    kb, e = local.shape
    d_pad = kb * block

    @jax.jit
    def f(w, local, mask):
        wb = jnp.pad(w, (0, d_pad - w.shape[0])).reshape(kb, block)

        def step(_, args):
            loc, msk, wslice = args
            onehot = (loc[:, None] ==
                      jnp.arange(block, dtype=jnp.int32)[None, :]
                      ).astype(jnp.bfloat16)
            out = jnp.dot(onehot, wslice.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
            return None, out * msk

        _, outs = lax.scan(step, None, (local, mask, wb))
        return outs.reshape(-1)  # packed [kb * e]

    return f, (w, local, mask)


def build_onehot_call(kb, e, interpret=False):
    """The raw pallas_call for the one-hot MXU gather candidate —
    separated from the data prep so the deviceless Mosaic compile gate
    (mosaic_aot_check.py) can AOT-compile it from abstract shapes.

    Two Mosaic constraints found by the AOT gate shape the geometry:
    the block shape's second-to-last dim must divide by 8 (a (1, ep)
    block fails to lower), and the materialized one-hot intermediate
    must FIT VMEM — so the grid is 2-D: 8 column-blocks per step along
    kb, ECOLS=512 entities per step along ep (one-hot tile
    [512, 2048] bf16 = 2 MB in VREGs, reused across the 8 static-loop
    2-D dots; no 3-D contraction). kb pads to a multiple of 8, e to a
    multiple of 512 (pad rows/cols gather w[.] masked to 0)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = 8  # second-to-last block dim must divide by 8
    ecols = 512  # entities per grid step: bounds the one-hot VMEM tile
    kbp = -(-kb // rows) * rows
    ep = -(-e // ecols) * ecols

    def kernel(loc_ref, msk_ref, w_ref, out_ref):
        iota = jax.lax.broadcasted_iota(jnp.int32, (ecols, BLOCK), 1)
        for i in range(rows):
            loc = loc_ref[i].reshape(ecols, 1)
            onehot = (loc == iota).astype(jnp.bfloat16)
            wv = w_ref[i].reshape(BLOCK, 1).astype(jnp.bfloat16)
            out = jnp.dot(onehot, wv, preferred_element_type=jnp.float32)
            out_ref[i] = out.reshape(ecols) * msk_ref[i]

    f = pl.pallas_call(
        kernel,
        grid=(kbp // rows, ep // ecols),
        in_specs=[
            pl.BlockSpec((rows, ecols), lambda b, c: (b, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, ecols), lambda b, c: (b, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, BLOCK), lambda b, c: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rows, ecols), lambda b, c: (b, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((kbp, ep), jnp.float32),
        interpret=interpret,
    )
    return f, ep, kbp


def make_pallas_onehot(w, local, mask, interpret=False):
    """Returns (jitted f, args). Timed over rolled (local, mask)."""
    import jax
    import jax.numpy as jnp

    kb, e = local.shape
    d_pad = kb * BLOCK
    w_pad = jnp.pad(w, (0, d_pad - w.shape[0])).reshape(kb, BLOCK)
    f, ep, kbp = build_onehot_call(kb, e, interpret=interpret)
    w_pad = jnp.pad(w_pad, ((0, kbp - kb), (0, 0)))
    local_p = jnp.pad(local, ((0, kbp - kb), (0, ep - e)))
    mask_p = jnp.pad(mask, ((0, kbp - kb), (0, ep - e)))
    jf = jax.jit(lambda l, m, wp: f(l, m, wp)[:kb, :e].reshape(-1))
    return jf, (local_p, mask_p, w_pad)


def _prep_residue(idx: np.ndarray, d: int):
    """Residue-class packing for Mosaic's lane-local dynamic_gather:
    the table reshapes to T[d/128, 128] (element j at sublane j//128,
    lane j%128) and tpu.dynamic_gather(T, C, [0]) lets lane l gather
    only from ITS OWN column T[:, l] — i.e. elements with j%128 == l.
    So indices are bucketed by residue j%128 (one stream per lane),
    each stream padded to a multiple of the table's sublane count A,
    giving C chunks of exactly the table's [A, 128] shape (the lowering
    requires x.shape == idx.shape). Returns (sub i32[chunks, A, 128],
    slot i64[m] mapping each original index to its packed position)."""
    assert d % 128 == 0
    a = d // 128
    lane = idx % 128
    sub = idx // 128
    order = np.argsort(lane, kind="stable")
    counts = np.bincount(lane, minlength=128)
    per_lane = -(-max(1, int(counts.max())) // a) * a  # pad to A-multiple
    chunks = per_lane // a
    packed = np.zeros((128, per_lane), np.int32)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(idx)) - np.repeat(starts, counts)
    packed[lane[order], pos] = sub[order]
    # [128, per_lane] -> [chunks, A, 128]
    packed = packed.reshape(128, chunks, a).transpose(1, 2, 0)
    slot = np.empty(len(idx), np.int64)
    # packed position (lane l, stream index p) -> flat slot in the
    # [chunks, A, 128] output: chunk = p // a, sublane = p % a, lane l.
    slot[order] = ((pos // a) * a * 128 + (pos % a) * 128
                   + lane[order])
    return packed, slot


def build_residue_call(chunks, a, lanes, dtype, interpret=False):
    """The raw pallas_call for the lane-local dynamic_gather candidate
    (separated from data prep for the deviceless Mosaic compile gate)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(w_ref, idx_ref, out_ref):
        out_ref[0] = jnp.take_along_axis(w_ref[:], idx_ref[0], axis=0)

    return pl.pallas_call(
        kernel,
        grid=(chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # whole table
            pl.BlockSpec((1, a, lanes), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, a, lanes), lambda t: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((chunks, a, lanes), dtype),
        interpret=interpret,
    )


def make_pallas_residue_gather(w, sub_chunks, interpret=False):
    """Whole table in VMEM as [d/128, 128]; one lane-local
    dynamic_gather per same-shape index chunk — the ONLY arbitrary-
    gather formulation Mosaic's gather lowering supports (jax pallas
    mosaic lowering.py:2464-2525: batched 2-D take_along_axis with
    slice_sizes (1,1); flat 1-D gathers raise 'Only 2D gather').
    Returns (jitted f, args)."""
    import jax
    import jax.numpy as jnp

    chunks, a, lanes = sub_chunks.shape
    w2 = jnp.asarray(w).reshape(a, lanes)
    f = build_residue_call(chunks, a, lanes, w.dtype, interpret=interpret)
    jf = jax.jit(lambda wt, i: f(wt, i).reshape(-1))
    sc = jnp.asarray(sub_chunks)
    return jf, (w2, sc)


REPS = 5  # distinct-arg timed reps per candidate

# Per-process nonce folded into every roll shift: two processes timing
# the same candidate in one tunnel window (e.g. chip_validation's run()
# then the watchdog's --sweep) must never enqueue byte-identical
# dispatches, or a relay-side result cache could serve one process the
# other's results.
_NONCE = os.getpid() % 997 + 1


def _variant_args(args, roll_axes, i):
    """Roll the arrays named by ``roll_axes`` (index -> axis) by a
    variant- and process-specific shift; arrays not named stay shared
    (e.g. the coefficient table). Rolled index/mask pairs shift
    TOGETHER so they stay aligned (paired arrays share an axis length,
    so the per-axis-length reduction below gives them the same
    effective shift), and a rolled workload has identical cost shape.

    The effective shift is forced NONZERO per rolled axis: a raw shift
    that happens to be a multiple of the axis length would make the
    roll an identity, re-opening the relay-side same-args caching hole
    this harness exists to close (ADVICE r5)."""
    import jax.numpy as jnp

    shift = (1009 + _NONCE) * i

    def roll(a, axis):
        eff = shift % a.shape[axis] or 1
        return jnp.roll(a, eff, axis=axis)

    return tuple(roll(a, roll_axes[j]) if j in roll_axes else a
                 for j, a in enumerate(args))


def _time_distinct(f, args, roll_axes):
    """args warms (and is the verify variant — never re-timed); each
    timed rep uses a distinct rolled variant so relay-side same-args
    result caching cannot serve a timed call (an un-hardened same-args
    loop once printed an impossible 256 G/s on the remote tunnel —
    docs/SCALE.md §methodology)."""
    import jax

    variants = [_variant_args(args, roll_axes, i + 1) for i in range(REPS)]
    jax.block_until_ready(f(*args))
    # The rolls above are async device work (~48 MB each at candidate
    # shapes); drain them BEFORE the clock starts or the timed window
    # absorbs roll cost (ADVICE r5).
    jax.block_until_ready(variants)
    t0 = time.perf_counter()
    outs = [f(*a) for a in variants]
    jax.block_until_ready(outs)
    return (time.perf_counter() - t0) / len(variants)


def run(m, d, check=False):
    import os

    import jax

    # Make JAX_PLATFORMS authoritative (a sitecustomize may force the
    # remote-TPU plugin and hang a CPU-intended run on tunnel init —
    # same guard as cli/__init__.py / bench.py).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    interpret = check and jax.default_backend() != "tpu"
    rng = np.random.default_rng(0)
    idx_np = rng.integers(0, d, m).astype(np.int32)
    w_np = rng.normal(0, 1, d).astype(np.float32)
    w = jnp.asarray(w_np)
    idx = jnp.asarray(idx_np)
    local, mask, slot = _prep_blocks(idx_np, d)
    local_j, mask_j = jnp.asarray(local), jnp.asarray(mask)
    res_chunks, res_slot = _prep_residue(idx_np, d)
    expect = w_np[idx_np]

    def verify(f, args, slot_map):
        out = np.asarray(f(*args))
        got = out[slot_map] if slot_map is not None else out
        np.testing.assert_allclose(got, expect, atol=2e-2)
        return True

    # candidate -> ((f, args), {arg index -> roll axis}, slot map)
    candidates = {
        "xla_gather": (make_xla_gather(w, idx), {1: 0}, None),
        "xla_onehot_scan": (make_xla_onehot_scan(w, local_j, mask_j),
                            {1: 1, 2: 1}, slot),
        "pallas_onehot": (make_pallas_onehot(w, local_j, mask_j,
                                             interpret=interpret),
                          {0: 1, 1: 1}, slot),
        "pallas_residue_gather": (
            make_pallas_residue_gather(w, res_chunks, interpret=interpret),
            {1: 1}, res_slot),
    }
    results = {}
    for name, ((f, args), roll_axes, slot_map) in candidates.items():
        try:
            verify(f, args, slot_map)
            dt = (_time_distinct(f, args, roll_axes) if not check
                  else float("nan"))
            results[name] = {"ok": True,
                             "mlookups_per_sec": (round(m / dt / 1e6, 1)
                                                  if dt == dt else None)}
        except Exception as e:  # noqa: BLE001 — report per-candidate
            results[name] = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps({"candidate": name, "m": m, "d": d,
                          **results[name]}), flush=True)
    return results


def sweep(m, d, blocks=(256, 512, 1024, 2048, 4096)):
    """Block-width sweep of xla_onehot_scan (round 5). The 2048-wide
    rate (293.6 M/s on chip) matches an MXU-GEMV bound — 770 G MAC/s
    (1/128 of peak, matrix-vector) / block MACs-per-lookup — so rate
    should scale ~1/block until the VPU one-hot generation or per-step
    scan overhead takes over. The sweep locates the knee."""
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    idx_np = rng.integers(0, d, m).astype(np.int32)
    w_np = rng.normal(0, 1, d).astype(np.float32)
    w = jnp.asarray(w_np)
    expect = w_np[idx_np]
    # Baseline closed through a reduction AND timed over distinct index
    # arrays per rep: an un-reduced same-args loop once printed an
    # impossible 256 G/s on the remote tunnel (result caching or DCE —
    # either way, the §methodology rule in docs/SCALE.md applies).
    f_base = jax.jit(lambda w, i: w[i].sum())
    base = _time_distinct(f_base, (w, jnp.asarray(idx_np)), {1: 0})
    print(json.dumps({"candidate": "xla_gather_reduced", "m": m, "d": d,
                      "ok": True,
                      "mlookups_per_sec": round(m / base / 1e6, 1)}),
          flush=True)
    for block in blocks:
        try:
            local, mask, slot = _prep_blocks(idx_np, d, block=block)
            f, args = make_xla_onehot_scan(
                w, jnp.asarray(local), jnp.asarray(mask), block=block)
            out = np.asarray(f(*args))
            np.testing.assert_allclose(out[slot], expect, atol=2e-2)
            dt = _time_distinct(f, args, {1: 1, 2: 1})
            res = {"ok": True,
                   "mlookups_per_sec": round(m / dt / 1e6, 1),
                   "pad_ratio": round(local.size / m, 3)}
        except Exception as e:  # noqa: BLE001 — report per-width
            res = {"ok": False, "error": f"{type(e).__name__}: {e}"[:200]}
        print(json.dumps({"candidate": f"xla_onehot_scan_b{block}",
                          "m": m, "d": d, **res}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="small-shape correctness check (CPU/interpret)")
    ap.add_argument("--sweep", action="store_true",
                    help="block-width sweep of the one-hot scan")
    ap.add_argument("--m", type=int, default=None)
    ap.add_argument("--d", type=int, default=None)
    args = ap.parse_args()
    if args.check:
        run(args.m or 3_000, args.d or 4_096, check=True)
    elif args.sweep:
        sweep(args.m or 12_000_000, args.d or 2_000_000)
    else:
        run(args.m or 12_000_000, args.d or 2_000_000)


if __name__ == "__main__":
    main()
