"""Deviceless Mosaic compile check: every Pallas kernel variant is
AOT-compiled for TPU v5e with the LOCAL libtpu compiler — no chip, no
tunnel, no interpret-mode proxy.

`jax.experimental.topologies.get_topology_desc("v5e:2x2")` builds a
compile-only PJRT client from the libtpu bundled in this image, and
`jax.jit(...).lower(...).compile()` against its abstract devices runs
the REAL Mosaic lowering + TPU backend compile. This closes the gap
VERDICT r4 weak #1 named: interpret-mode parity proves semantics, not
that Mosaic legalizes the kernel (it immediately caught a real one:
vector-valued `scf.if` from the line-search tail's `lax.cond` fails to
legalize — now KERNEL.md constraint #6, fixed as a 0/1-trip
while_loop).

Run after any kernel change (and in CI-like gates):
    python dev_scripts/mosaic_aot_check.py            # all variants
    python dev_scripts/mosaic_aot_check.py lbfgs owlqn # name filter

Exit 0 iff every selected variant compiles. This does NOT execute
anything (abstract devices) — chip_validation.py remains the on-chip
run gate; this is the compile gate.
"""

from __future__ import annotations

import functools
import os
import sys
import time


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.pallas_entity_solver import pallas_entity_lbfgs
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.utils.aot import v5e_topology

    topo = v5e_topology()
    sh = NamedSharding(Mesh(np.array(topo.devices[:1]), ("x",)),
                       PartitionSpec())

    def arg(shape, dt=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dt, sharding=sh)

    log_loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    poi_loss = loss_for_task(TaskType.POISSON_REGRESSION)
    e, r, d = 256, 8, 6
    base = (arg((e, r, d)), arg((e, r)), arg((e, r)), arg((e, r)),
            arg((e, d)), arg(()), arg(()))
    norm = dict(factors=arg((e, d)), shifts=arg((e, d)))
    bnds = dict(lower=arg((e, d)), upper=arg((e, d)))

    variants = [
        ("lbfgs", log_loss, "lbfgs", {}),
        ("owlqn", log_loss, "owlqn", {}),
        ("tron", poi_loss, "tron", {}),
        ("lbfgs+norm", log_loss, "lbfgs", dict(norm)),
        ("lbfgs+bounds", log_loss, "lbfgs", dict(bnds)),
        ("lbfgs+norm+bounds", log_loss, "lbfgs", dict(**norm, **bnds)),
        ("owlqn+norm", log_loss, "owlqn", dict(norm)),
        ("tron+norm", poi_loss, "tron", dict(norm)),
        ("tron+bounds", poi_loss, "tron", dict(bnds)),
        ("tron+norm+bounds", poi_loss, "tron", dict(**norm, **bnds)),
    ]
    selected = sys.argv[1:]
    failures = []
    ran = [0]

    def run_group(checks):
        """Shared check runner: time each (name, thunk), print one line,
        record failures (exit-code accounting happens at the end)."""
        for name, thunk in checks:
            ran[0] += 1
            t0 = time.perf_counter()
            try:
                thunk()
                print(f"{name:28s}: MOSAIC COMPILE OK "
                      f"({time.perf_counter() - t0:.1f}s)", flush=True)
            except Exception as ex:  # noqa: BLE001
                failures.append(name)
                first = str(ex).strip().splitlines()
                print(f"{name:28s}: FAILED — "
                      f"{first[0][:160] if first else ex}", flush=True)
    def variant_checks():
        for name, loss, mode, kw in variants:
            if selected and not any(s in name for s in selected):
                continue
            fn = functools.partial(pallas_entity_lbfgs, loss, max_iter=15,
                                   tol=1e-6, mode=mode)
            yield name, functools.partial(
                lambda fn_, kw_: jax.jit(fn_).lower(*base, **kw_).compile(),
                fn, kw)

    run_group(variant_checks())
    # Multi-chip compiles: the SAME paths the virtual-CPU dryrun executes,
    # but compiled for a real v5e 2x2 slice — XLA lowers the sharding
    # annotations to actual ICI collectives, something no CPU mesh can
    # certify.
    def shard_checks():
        from photon_ml_tpu.ops.features import DenseFeatures
        from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
        from photon_ml_tpu.optimization.convergence import OptimizerResult
        from photon_ml_tpu.optimization.glm_lbfgs import minimize_lbfgs_glm

        mesh4 = Mesh(np.array(topo.devices), ("data",))

        def marg(shape, spec, dt=jnp.float32):
            return jax.ShapeDtypeStruct(
                shape, dt, sharding=NamedSharding(mesh4, spec))

        s2, s3 = PartitionSpec("data", None), PartitionSpec("data", None,
                                                            None)
        out_specs = OptimizerResult(
            x=s2, value=PartitionSpec("data"),
            grad_norm=PartitionSpec("data"),
            iterations=PartitionSpec("data"), reason=PartitionSpec("data"),
            value_history=None, grad_norm_history=None, coef_history=None)
        kfn = functools.partial(pallas_entity_lbfgs, log_loss, max_iter=15,
                                tol=1e-6, mode="lbfgs")
        sharded_kernel = jax.shard_map(
            lambda x, y, o, w, c0: kfn(x, y, o, w, c0, 1.0), mesh=mesh4,
            in_specs=(s3, s2, s2, s2, s2),
            out_specs=out_specs, check_vma=False)
        ep = 4 * 256
        yield "kernel@shard_map(4 chips)", lambda: jax.jit(
            sharded_kernel).lower(
                marg((ep, r, d), s3), marg((ep, r), s2), marg((ep, r), s2),
                marg((ep, r), s2), marg((ep, d), s2)).compile()

        obj = GLMObjective(log_loss)
        n, dfe = 1024, 64
        dp = PartitionSpec("data")
        batch = GLMBatch(
            DenseFeatures(marg((n, dfe), s2)), marg((n,), dp),
            marg((n,), dp), marg((n,), dp))
        fe_fn = functools.partial(minimize_lbfgs_glm, obj, l2_weight=1.0,
                                  max_iter=20, tol=0.0)
        yield "fe_lbfgs@dp(4 chips)", lambda: jax.jit(
            lambda b, x0: fe_fn(b, x0)).lower(
                batch, marg((dfe,), PartitionSpec())).compile()

        # Feature-dimension ("model") sharding on a 2x2 (data x model)
        # mesh: coefficient columns sharded, margins all-reduced over ICI.
        mesh22 = Mesh(np.array(topo.devices).reshape(2, 2),
                      ("data", "model"))

        def marg22(shape, spec, dt=jnp.float32):
            return jax.ShapeDtypeStruct(
                shape, dt, sharding=NamedSharding(mesh22, spec))

        batch22 = GLMBatch(
            DenseFeatures(marg22((n, dfe), PartitionSpec("data", "model"))),
            marg22((n,), PartitionSpec("data")),
            marg22((n,), PartitionSpec("data")),
            marg22((n,), PartitionSpec("data")))
        yield "fe_lbfgs@dpxmp(2x2 chips)", lambda: jax.jit(
            lambda b, x0: fe_fn(b, x0)).lower(
                batch22, marg22((dfe,), PartitionSpec("model"))).compile()

        # A full v5e-16 slice (4x4): the composed data x model mesh at
        # the largest single-host v5e topology — collectives lower for
        # a 16-chip ICI ring, not just the 4-chip square. Topology
        # creation happens INSIDE the thunk so a libtpu that rejects
        # the name records as this one check failing, not a gate crash.
        def check_4x4():
            topo16 = v5e_topology("v5e:4x4")
            mesh44 = Mesh(np.array(topo16.devices).reshape(4, 4),
                          ("data", "model"))

            def marg44(shape, spec, dt=jnp.float32):
                return jax.ShapeDtypeStruct(
                    shape, dt, sharding=NamedSharding(mesh44, spec))

            batch44 = GLMBatch(
                DenseFeatures(marg44((n, dfe),
                                     PartitionSpec("data", "model"))),
                marg44((n,), PartitionSpec("data")),
                marg44((n,), PartitionSpec("data")),
                marg44((n,), PartitionSpec("data")))
            return jax.jit(lambda b, x0: fe_fn(b, x0)).lower(
                batch44, marg44((dfe,), PartitionSpec("model"))).compile()

        yield "fe_lbfgs@dpxmp(4x4 chips)", check_4x4

    # Gather-wall candidates (docs/SCALE.md): the two Pallas candidates
    # and the XLA one-hot scan, compiled at the d=2M bench geometry.
    # Compile certainty here; the integrate-or-close decision still needs
    # chip TIMING (chip_validation.py runs them).
    def gather_checks():
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from gather_experiments import (
            BLOCK,
            build_onehot_call,
            build_residue_call,
        )

        d_g, m_g = 2_000_000, 12_000_000
        kb = -(-d_g // BLOCK)
        e_g = -(-m_g // kb)  # balanced per-block count
        f_oh, ep, kbp = build_onehot_call(kb, e_g)
        yield "gather:pallas_onehot", lambda: jax.jit(
            lambda l, m_, wp: f_oh(l, m_, wp)).lower(
                arg((kbp, ep), jnp.int32), arg((kbp, ep)),
                arg((kbp, BLOCK))).compile()

        # The residue dynamic_gather candidate is compiler-capped: the
        # gather dim must fit ONE source vreg (8 f32 sublanes -> tables
        # of <=1024 elements), so it can only compile at tiny d. Verify
        # the cap from both sides: a=8 must compile, the d=2M geometry
        # must fail with 'Multiple source vregs'.
        f_small = build_residue_call(4, 8, 128, jnp.float32)
        yield "gather:residue(d=1024 cap)", lambda: jax.jit(
            lambda wt, i: f_small(wt, i)).lower(
                arg((8, 128)), arg((4, 8, 128), jnp.int32)).compile()

        def residue_big_must_fail():
            a_g = -(-d_g // 128)
            chunks = -(-(m_g // 128) // a_g)
            f_rg = build_residue_call(chunks, a_g, 128, jnp.float32)
            try:
                jax.jit(lambda wt, i: f_rg(wt, i)).lower(
                    arg((a_g, 128)), arg((chunks, a_g, 128),
                                         jnp.int32)).compile()
            except Exception as ex:  # noqa: BLE001
                if "Multiple source vregs" in str(ex):
                    return  # the documented architectural cap holds
                raise
            raise AssertionError(
                "residue gather at d=2M unexpectedly compiled — revisit "
                "SCALE.md's impossibility note")

        yield "gather:residue(d=2M is capped)", residue_big_must_fail

    if not selected or any("gather".startswith(s) for s in selected):
        run_group(gather_checks())

    # Sort-permutation sparse layout (docs/SCALE.md §Attacking the
    # gather wall): both products compile for v5e at the d=2M bench
    # geometry — a ~12M-element (i32, f32) lax.sort per pass plus the
    # broadcast expansions and fixed-width reductions. Compile certainty
    # here; the integrate-or-close decision needs the chip sort RATE
    # (dev_scripts/sort_primitives.py).
    def sortperm_checks():
        from photon_ml_tpu.ops.features import SortPermuteEllFeatures

        n_r, d_c, w_r = 250_000, 2_000_000, 48
        col_groups = [(1_500_000, 7), (500_000, 4)]
        p = max(n_r * w_r, sum(ng * wg for ng, wg in col_groups))
        feats = SortPermuteEllFeatures(
            row_vals=(arg((n_r, w_r)),),
            row_owner=(arg((n_r,), jnp.int32),),
            row_inv=arg((n_r,), jnp.int32),
            col_vals=tuple(arg((ng, wg)) for ng, wg in col_groups),
            col_owner=tuple(arg((ng,), jnp.int32) for ng, _ in col_groups),
            col_inv=arg((d_c,), jnp.int32),
            keys_c2r=arg((p,), jnp.int32),
            keys_r2c=arg((p,), jnp.int32),
            n_rows=n_r, n_features=d_c)
        yield "sortperm:matvec(d=2M)", lambda: jax.jit(
            lambda f, v: f.matvec(v)).lower(feats, arg((d_c,))).compile()
        yield "sortperm:rmatvec(d=2M)", lambda: jax.jit(
            lambda f, u: f.rmatvec(u)).lower(feats, arg((n_r,))).compile()

    # Prefix match, not reversed substring membership: `any(s in "sortperm")`
    # would let selectors like "t" or "o" silently enable unrelated groups
    # (ADVICE r5).
    if not selected or any("sortperm".startswith(s) for s in selected):
        run_group(sortperm_checks())

    if not selected or any("sharded".startswith(s) for s in selected):
        run_group(shard_checks())

    if failures:
        print(f"FAILED VARIANTS: {failures}")
        return 1
    if selected and not ran[0]:
        # A selector that matches nothing must fail loudly, not certify
        # zero compiles as green (group selectors PREFIX-match 'gather'/
        # 'sortperm'/'sharded'; variant selectors substring-match names).
        print(f"NO CHECKS MATCHED SELECTORS {selected!r}")
        return 2
    print("ALL SELECTED VARIANTS COMPILE ON MOSAIC (v5e, deviceless AOT)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
