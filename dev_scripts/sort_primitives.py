"""Chip probe: can sort-based data movement beat the random-access wall?

The sparse iteration's irreducible cost is applying a FIXED permutation
(packed order <-> ELL order) and a FIXED one-to-many expansion
(coefficient space -> slot space). Both are gathers today (~115-148 M
lookups/s flat). Alternatives measured here, all sequential-access
(one JSON line per op; docs/SCALE.md section "Attacking the gather
wall" has the cost model these rates plug into):

  sort12M_kv        lax.sort of (i32 key, f32 payload) at m=12M — the
                    cost of applying a known permutation via sort.
  sort12M_keyonly   key alone (lower bound for the sort machinery).
  cumsum12M         prefix scan at 12M — run-length copy-forward cost.
  max_scan12M       associative max-scan (segmented-propagate shape).
  scatter2M_into_12M  scatter of 2M run heads into a 12M vector.
  gather12M_reduced the baseline wall, reduction-closed against DCE.

Timing uses gather_experiments._time_distinct: every timed rep gets a
distinct per-process rolled input, so neither DCE nor relay-side
same-args result caching (docs/SCALE.md §methodology) can fake a rate.

Usage: python dev_scripts/sort_primitives.py [--m 12000000] [--d 2000000]
"""
import argparse
import json
import os

import numpy as np

from gather_experiments import _time_distinct


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--m", type=int, default=12_000_000)
    ap.add_argument("--d", type=int, default=2_000_000)
    args = ap.parse_args()
    m, d = args.m, args.d

    import jax

    # Make JAX_PLATFORMS authoritative (a sitecustomize may force the
    # remote-TPU plugin and hang a CPU-intended run on tunnel init —
    # same guard as gather_experiments.py / bench.py).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.default_rng(11)
    # a rolled permutation is still a permutation, so the shared
    # roll-variant harness keeps every op's input valid
    keys = jnp.asarray(rng.permutation(m).astype(np.int32))
    vals = jnp.asarray(rng.normal(0, 1, m).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, d, m).astype(np.int32))
    w = jnp.asarray(rng.normal(0, 1, d).astype(np.float32))
    heads = jnp.asarray(
        np.sort(rng.choice(m, d, replace=False)).astype(np.int32))
    hv = jnp.asarray(rng.normal(0, 1, d).astype(np.float32))

    @jax.jit
    def f_sort(k, v):
        sk, sv = lax.sort((k, v), num_keys=1)
        return sv.sum(), sk[-1]

    @jax.jit
    def f_sortk(k):
        return lax.sort(k)[-1]

    @jax.jit
    def f_cumsum(v):
        return jnp.cumsum(v).sum()

    @jax.jit
    def f_max_scan(v):
        # copy-forward of run heads is a segmented scan; the plain
        # associative max-scan over the values bounds its cost shape.
        return lax.associative_scan(jnp.maximum, v).sum()

    @jax.jit
    def f_scatter(hv):
        z = jnp.zeros(m, jnp.float32)
        return z.at[heads].add(hv).sum()

    @jax.jit
    def f_gather(w, idx):
        return w[idx].sum()

    # op -> (jitted f, args, {arg index -> roll axis})
    suites = [
        ("gather12M_reduced", f_gather, (w, idx), {1: 0}),
        ("sort12M_kv", f_sort, (keys, vals), {0: 0}),
        ("sort12M_keyonly", f_sortk, (keys,), {0: 0}),
        ("cumsum12M", f_cumsum, (vals,), {0: 0}),
        ("max_scan12M", f_max_scan, (vals,), {0: 0}),
        ("scatter2M_into_12M", f_scatter, (hv,), {0: 0}),
    ]
    for name, f, fargs, roll_axes in suites:
        try:
            ms = _time_distinct(f, fargs, roll_axes) * 1e3
            print(json.dumps({"op": name, "m": m, "d": d,
                              "ms": round(ms, 2),
                              "melem_per_sec": round(m / ms / 1e3, 1)}),
                  flush=True)
        except Exception as e:  # noqa: BLE001 — report per-op
            print(json.dumps({"op": name, "m": m, "d": d,
                              "error": str(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
