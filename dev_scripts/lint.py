#!/usr/bin/env python3
"""Dependency-free lint gate — the reference wires scalastyle + Apache RAT
into its `check` task (/root/reference/build.gradle:48+,
scalastyle-config.xml); this is the same discipline for a Python/JAX tree
using only the stdlib (no ruff/flake8 in the image).

Checks, per file:
  syntax        file must parse (ast.parse)
  tabs          no tab indentation
  trailing-ws   no trailing whitespace
  line-length   <= 99 columns
  bare-except   no `except:` without an exception class
  mutable-default  no list/dict/set literals as parameter defaults
  star-import   no `from x import *`
  unused-import imported name never referenced (skipped in __init__.py,
                which re-exports; names starting with _ are exempt)

Exit 0 = clean. Run via tests.sh or directly:
    python dev_scripts/lint.py [paths...]
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 99
DEFAULT_PATHS = ["photon_ml_tpu", "tests", "dev_scripts", "bench.py",
                 "__graft_entry__.py"]


def _imported_names(tree: ast.AST):
    """(local_name, node) for every import binding."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.append(((a.asname or a.name).split(".")[0], node))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name != "*":
                    out.append((a.asname or a.name, node))
    return out


def _used_names(tree: ast.AST):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # Identifier-shaped strings count as uses: string type
            # annotations (PEP 563 forward refs, incl. dotted forms like
            # 'np.ndarray') and __all__ entries.
            for tok in (node.value.replace("[", " ").replace("]", " ")
                        .replace(".", " ").replace(",", " ").split()):
                if tok.isidentifier():
                    used.add(tok)
    return used


def lint_file(path: Path, src: str = None) -> list:
    """``src`` lets a caller that already read the file (dev_scripts/
    jaxlint.py's shared walk) skip the second read."""
    problems = []
    if src is None:
        src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]

    for i, line in enumerate(src.splitlines(), 1):
        if line != line.rstrip():
            problems.append((path, i, "trailing whitespace"))
        if "\t" in line:
            problems.append((path, i, "tab character"))
        if len(line) > MAX_LINE:
            problems.append((path, i, f"line length {len(line)} > {MAX_LINE}"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append((path, node.lineno, "bare except"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None]:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        (path, d.lineno, "mutable default argument"))
        elif isinstance(node, ast.ImportFrom):
            if any(a.name == "*" for a in node.names):
                problems.append((path, node.lineno, "star import"))

    if path.name != "__init__.py":
        used = _used_names(tree)
        for name, node in _imported_names(tree):
            if name.startswith("_") or name in used:
                continue
            problems.append((path, node.lineno, f"unused import {name!r}"))
    return problems


def main(argv) -> int:
    roots = [Path(p) for p in (argv or DEFAULT_PATHS)]
    files = []
    for r in roots:
        files += sorted(r.rglob("*.py")) if r.is_dir() else [r]
    problems = []
    for f in files:
        problems += lint_file(f)
    for path, line, msg in problems:
        print(f"{path}:{line}: {msg}")
    print(f"lint: {len(files)} files, {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
