#!/usr/bin/env python3
"""jaxlint CLI — the JAX-aware static analysis gate.

Sits next to dev_scripts/lint.py in tests.sh's lint phase (one shared
file walk): lint.py keeps the tree tidy, jaxlint keeps it fast. Rules
(photon_ml_tpu/analysis/rules.py, catalog in docs/ANALYSIS.md):

  retrace-hazard            per-call recompilation patterns
  host-sync                 device->host syncs inside jit-reachable code
  dtype-drift               f32-parity-unsafe dtypes on device paths
  nondeterministic-pytree   set-ordered pytree leaves / cache keys

The gate is "no NEW violations": pre-existing accepted findings live in
dev_scripts/jaxlint_baseline.txt (fingerprints are line-number-free, so
the baseline survives unrelated edits). Inline escape hatch, on the
violating line:  # jaxlint: disable=<rule>[,<rule>...]

Usage:
    python dev_scripts/jaxlint.py [paths...]
    python dev_scripts/jaxlint.py --baseline-update   # regenerate baseline
    python dev_scripts/jaxlint.py --with-style        # + lint.py checks
    python dev_scripts/jaxlint.py --list-rules

Exit 0 = no new violations (and, with --with-style, no style problems).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from photon_ml_tpu import analysis  # noqa: E402

try:
    from dev_scripts import lint as style_lint
except ImportError:  # run as a script: dev_scripts/ itself is sys.path[0]
    import lint as style_lint

# jaxlint's default scope: the package + tooling. tests/ is style-checked
# (via --with-style) but exempt from jaxlint rules — tests legitimately
# jit per call and host-sync eagerly.
ANALYSIS_PATHS = ["photon_ml_tpu", "dev_scripts", "bench.py",
                  "__graft_entry__.py"]
DEFAULT_BASELINE = REPO_ROOT / "dev_scripts" / "jaxlint_baseline.txt"


def _resolve(paths, root: Path, strict: bool = False):
    """Default paths that don't exist are skipped (not every tree has a
    bench.py); EXPLICIT paths that don't exist are an error — a typo'd
    path silently analyzing 0 files would pass the gate vacuously."""
    out = []
    for p in paths:
        q = Path(p)
        q = q if q.is_absolute() else root / q
        if not q.exists():
            if strict:
                raise SystemExit(f"jaxlint: path not found: {p}")
            continue
        out.append(q)
    return out


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="jaxlint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to analyze (default: "
                         f"{' '.join(ANALYSIS_PATHS)})")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE)
    ap.add_argument("--root", type=Path, default=REPO_ROOT,
                    help="tree root for path-relative fingerprints and "
                         "default-path resolution (tests use tmp trees)")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(sorted, path-relative, deterministic)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation, ignoring the baseline")
    ap.add_argument("--with-style", action="store_true",
                    help="also run dev_scripts/lint.py checks over one "
                         "shared file walk (tests.sh's lint phase)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in analysis.ALL_RULES:
            print(f"{rule.id}: {rule.doc}")
        return 0

    root = args.root.resolve()
    explicit = bool(args.paths)
    if args.baseline_update and explicit:
        print("jaxlint: --baseline-update regenerates the FULL baseline "
              "and must not be scoped to a path subset (accepted entries "
              "outside the subset would be silently dropped); run it "
              "without explicit paths")
        return 2
    jax_paths = _resolve(args.paths or ANALYSIS_PATHS, root,
                         strict=explicit)

    # ONE walk, ONE read per file; each tool consumes its subset
    # (lint.py takes the preloaded source via lint_file(..., src)).
    # Style-only paths (tests/, ...) join the walk only when style
    # checks actually run.
    if args.with_style:
        style_paths = jax_paths if explicit else _resolve(
            style_lint.DEFAULT_PATHS, root)
    else:
        style_paths = []
    all_files = analysis.iter_py_files(sorted(set(style_paths)
                                              | set(jax_paths)))
    sources = {f: f.read_text() for f in all_files}
    jax_roots = tuple(p.resolve() for p in jax_paths)
    jax_files = [f for f in all_files
                 if any(f.resolve() == r or r in f.resolve().parents
                        for r in jax_roots)]

    style_problems = []
    if args.with_style:
        style_set = {f.resolve() for f in analysis.iter_py_files(
            style_paths)}
        for f in all_files:
            if f.resolve() in style_set:
                style_problems += style_lint.lint_file(f, src=sources[f])
        for path, line, msg in style_problems:
            print(f"{path}:{line}: {msg}")

    modules = []
    for f in jax_files:
        try:
            rel = f.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = f.as_posix()
        mod = analysis.core.parse_module(rel, sources[f])
        if mod is not None:
            modules.append(mod)
    violations = analysis.analyze_modules(modules)

    if args.baseline_update:
        analysis.write_baseline(args.baseline, violations)
        print(f"jaxlint: baseline updated — {len(violations)} accepted "
              f"finding(s) in {args.baseline.name}")
        return 0

    baseline = (analysis.load_baseline(args.baseline)
                if not args.no_baseline else None)
    if baseline is not None:
        new, stale = analysis.apply_baseline(violations, baseline)
    else:
        new, stale = list(violations), {}

    for v in new:
        print(v.render())
    if stale:
        print(f"jaxlint: note — {sum(stale.values())} stale baseline "
              "entry(ies) no longer match any finding; run "
              "--baseline-update to tidy:")
        for fp in sorted(stale):
            print(f"  stale: {fp}")
    print(f"jaxlint: {len(jax_files)} files, {len(violations)} finding(s),"
          f" {len(new)} new"
          + (f"; style: {len(style_problems)} problem(s)"
             if args.with_style else ""))
    return 1 if (new or style_problems) else 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
