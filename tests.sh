#!/usr/bin/env bash
# Check gate: lint + full test suite — the analog of the reference's
# `tests.sh` / gradle `check` (scalastyle + RAT + tests,
# /root/reference/build.gradle:48+). One command, green in a fresh clone:
#     ./tests.sh [pytest args...]
set -euo pipefail
cd "$(dirname "$0")"

echo "== lint =="
python dev_scripts/lint.py

echo "== tests =="
python -m pytest tests/ -q "$@"
