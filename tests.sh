#!/usr/bin/env bash
# Check gate: lint + full test suite — the analog of the reference's
# `tests.sh` / gradle `check` (scalastyle + RAT + tests,
# /root/reference/build.gradle:48+). One command, green in a fresh clone:
#     ./tests.sh [pytest args...]
set -euo pipefail
cd "$(dirname "$0")"

echo "== lint =="
# One phase, one file walk: style checks (dev_scripts/lint.py) + the
# JAX-aware static analysis gate (dev_scripts/jaxlint.py, docs/ANALYSIS.md).
python dev_scripts/jaxlint.py --with-style
# Metric-name schema gate (dotted snake_case, no conflicting-type
# registrations — docs/OBSERVABILITY.md §Prometheus naming).
python dev_scripts/metric_names.py

echo "== tests =="
python -m pytest tests/ -q "$@"
